"""Zero-downtime model hot-swap with verified rollback.

:class:`HotSwapper` rolls a serving unit — one ``MicroBatcher`` or every
healthy replica under a :class:`~photon_ml_tpu.serving.supervisor.
ReplicaSupervisor` — onto a new model directory without dropping a
request:

1. **Load** the new model ONCE off the request path, through the PR-3
   fingerprint sidecars (``io/model_store.py`` / ``io/game_store.py``) —
   a tampered payload or ``.meta.json`` raises here, before anything is
   built, and the old version keeps serving.
2. **Prepare**: build one fresh ``ScoringRuntime`` per target from the
   shared host-side model (per-replica LRU hot sets start cold), warm
   the bucket-ladder kernels, and score a verification probe directly on
   each new runtime (finite scores or abort).
3. **Commit**: assign ``batcher.runtime = new_runtime`` on every target.
   The dispatch loop reads the attribute once per batch, so the
   assignment is the atomic cutover — in-flight batches finish on the
   old runtime, the next batch scores on the new one.  No request ever
   observes a half-swapped runtime.
4. **Verify**: score a probe THROUGH each target's real dispatch path.
   A failed probe (or a scripted ``serving.swap`` fault) restores the
   previous runtimes — one-step rollback, counted on
   ``serving_rollbacks_total``.

The previous version is retained after a successful swap for one-step
manual :meth:`rollback` (``POST /reload {"rollback": true}``).

**Pinned decision** — a swap requested while any target runtime is
``degraded=True`` (PR-6 host path) is **deferred**: the result reports
``"deferred"``, nothing changes, and ``serving_swaps_deferred_total``
counts it.  Degraded means the device path is suspect; committing a new
runtime whose hot tables live on that same device would "verify" through
the host fallback and mask a broken swap.  Recover the device first (the
breaker re-promotes) or restart the replica, then reload.

Versions are monotone integers stamped on each runtime
(``model_version``; the initial load is version 1) and surfaced on the
``serving_model_version`` gauge, ``/healthz``, and ``/stats``.

Chaos: the ``serving.swap`` site is touched at stages ``load`` /
``prepare`` / ``verify`` (occurrences 0/1/2 per swap attempt), so a
FaultPlan can script both the abort path (pre-commit) and the rollback
path (post-commit) — see docs/robustness.md.

**Process mode** (targets are :class:`~photon_ml_tpu.serving.procpool.
ProcessReplica` stubs): the same four stages run over the worker swap
protocol.  Load publishes the new model ONCE into shared memory as a
staged pool generation; prepare asks each worker to attach + warm +
probe it off its request path (``swap_prepare``); commit is each
worker's own GIL-atomic ``batcher.runtime`` assignment
(``swap_commit``); verify scores through each worker's real dispatch
path from the parent.  Failure anywhere unwinds: staged attachments are
aborted, committed workers ``swap_rollback``, and the staged segments
are unlinked.  Success promotes the generation
(``pool.commit_generation`` — the last TWO generations stay linked, so
a worker respawned mid-window can still attach) and manual rollback
walks workers back one step and restores the prior generation.  A
worker RESTARTED after the commit attached the new generation directly
and holds no worker-side previous; rollback detects that (the worker
answers ``rolled_back: false``) and kills it, so it respawns on the
restored generation — convergence costs one restart, never a wrong
version left serving.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Optional, Sequence

import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
from photon_ml_tpu.serving.tenancy import tenant_slug


class SwapInProgressError(RuntimeError):
    """A second /reload arrived while a swap was still running.  Swaps
    are serialized — concurrent swaps would race the commit point and
    leave targets on mixed versions."""


@dataclasses.dataclass
class SwapResult:
    """Outcome of one swap attempt (the /reload response body)."""

    status: str  # "swapped" | "rolled_back" | "deferred"
    version_before: int
    version_after: int
    model_path: Optional[str]
    #: how far the attempt got: "load" | "prepare" | "verify" | "commit"
    stage: str = "commit"
    reason: Optional[str] = None
    targets: int = 0
    #: set on tenant-scoped swaps/rollbacks: only this tenant's route
    #: moved; the default route and every other tenant are untouched.
    tenant: Optional[str] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


class HotSwapper:
    """Owns model-version state and the swap/rollback state machine for
    one serving unit.

    ``targets_fn`` returns the live ``MicroBatcher`` list to roll (the
    service supplies it: one batcher standalone, the healthy replicas'
    batchers under a supervisor).  ``on_commit`` (optional) is called
    after every successful swap OR rollback with the now-serving
    ``(model, index_maps, config, version, path)`` — the supervisor uses
    it to rebuild its replica factory so restarts come back on the
    serving version.
    """

    def __init__(
        self,
        targets_fn: Callable[[], Sequence],
        on_commit: Optional[Callable] = None,
        on_kill: Optional[Callable] = None,
        on_tenant_commit: Optional[Callable] = None,
        probe_timeout_s: float = 30.0,
    ):
        self._targets_fn = targets_fn
        self._on_commit = on_commit
        #: tenant-route durability hook: called after every successful
        #: TENANT swap or rollback with ``(tenant, model, index_maps,
        #: config, version, path)`` — all-None payload means the tenant
        #: fell back onto the default route.  The supervisor uses it to
        #: re-apply tenant routes on replicas it restarts (thread mode;
        #: in pool mode the pool's tenant-generation registry replays
        #: routes into respawned workers instead).
        self._on_tenant_commit = on_tenant_commit
        #: convergence-kill hook: called with (target, reason) when the
        #: rollback must kill a worker that holds no retained previous.
        #: A supervisor-backed service routes this through kill_replica
        #: so the mark-down is SYNCHRONOUS with the rollback — healthz
        #: never reports the converge-killed worker healthy, and a
        #: caller that awaits health after rollback() waits for the
        #: respawn instead of racing stale state.
        self._on_kill = on_kill
        self.probe_timeout_s = probe_timeout_s
        self._swap_lock = sanitizers.tracked(
            threading.Lock(), "serving.swap"
        )
        #: readiness hook: True between /reload accept and commit+verify.
        self.in_progress = False
        self.version = 1
        #: high-water mark: version numbers are NEVER reused, so the
        #: sequence of committed swaps is strictly monotone even across
        #: a manual rollback (rollback lowers ``version``, not this).
        self._max_version = 1
        self.model_path: Optional[str] = None
        #: (target, previous_runtime) pairs retained for one-step rollback.
        self._previous: list[tuple] = []
        #: process-mode rollback token: (pool, version_before) after a
        #: successful remote swap (the runtimes to restore live in the
        #: workers and the pool's generation list, not here).
        self._remote_previous: Optional[tuple] = None
        #: tenant → (version, model_path) for every committed
        #: tenant-scoped route.  Tenants absent here follow the default
        #: route (``self.version``).  Versions come from the SAME
        #: monotone ``_max_version`` sequence as full swaps.
        self._tenant_versions: dict = {}
        #: one-step tenant rollback token, set by the last successful
        #: tenant swap: ("thread", tenant, [(target, old_route)], prev)
        #: or ("process", tenant, pool, prev) where prev is the
        #: registry entry the swap displaced (None = default route).
        self._tenant_previous: Optional[tuple] = None
        self.swaps = 0
        self.rollbacks = 0
        self.deferred = 0

    # -- observability -------------------------------------------------------
    def adopt_version(self, runtime) -> None:
        """Sync the swapper's version identity from an already-serving
        runtime (called by the service at construction)."""
        self.version = getattr(runtime, "model_version", 1)
        self._max_version = max(self._max_version, self.version)
        self.model_path = getattr(runtime, "model_path", None)
        telemetry_mod.current().gauge("serving_model_version").set(
            self.version
        )

    def tenant_versions(self) -> dict:
        """tenant → (version, model_path) for every committed
        tenant-scoped route (snapshot copy)."""
        return dict(self._tenant_versions)

    def stats(self) -> dict:
        return {
            "model_version": self.version,
            "model_path": self.model_path,
            "in_progress": self.in_progress,
            "swaps": self.swaps,
            "rollbacks": self.rollbacks,
            "deferred": self.deferred,
            "can_rollback": bool(self._previous)
            or self._remote_previous is not None,
            "tenant_versions": {
                t: {"version": v, "model_path": p}
                for t, (v, p) in self._tenant_versions.items()
            },
        }

    # -- the swap state machine ----------------------------------------------
    def swap(
        self,
        model_path: str,
        runtime_config: Optional[RuntimeConfig] = None,
        tenant: Optional[str] = None,
    ) -> SwapResult:
        """Roll every live target onto the model at ``model_path``.

        With ``tenant`` set, only that tenant's ROUTE moves: every
        target keeps its default runtime, and rows carrying the tenant
        id score against the new version (serving/tenancy.py).  The
        default route and every other tenant are bitwise untouched.

        Never raises for a failed swap — the failure IS the result
        (status ``"rolled_back"`` with the stage and reason), because
        the old version is still serving and the caller needs to report
        that, not crash.  Only :class:`SwapInProgressError` (concurrent
        /reload) propagates.
        """
        if not self._swap_lock.acquire(blocking=False):
            raise SwapInProgressError(
                "a model swap is already in progress; retry after it "
                "completes"
            )
        try:
            self.in_progress = True
            if tenant is not None:
                return self._swap_tenant_locked(
                    tenant, model_path, runtime_config
                )
            return self._swap_locked(model_path, runtime_config)
        finally:
            self.in_progress = False
            self._swap_lock.release()

    def _swap_locked(
        self, model_path: str, runtime_config: Optional[RuntimeConfig]
    ) -> SwapResult:
        tel = telemetry_mod.current()
        version_before = self.version
        new_version = self._max_version + 1
        targets = list(self._targets_fn())
        if not targets:
            return self._rolled_back(
                version_before, model_path, "load",
                "no live targets to swap", 0,
            )
        if any(
            getattr(t.runtime, "degraded", False) for t in targets
        ):
            # Pinned: defer, never swap through a degraded device
            # (module docstring).
            self.deferred += 1
            tel.counter("serving_swaps_deferred_total").inc()
            tel.event(
                "serving.swap_deferred",
                model_path=model_path,
                version=version_before,
            )
            return SwapResult(
                status="deferred",
                version_before=version_before,
                version_after=version_before,
                model_path=model_path,
                stage="load",
                reason="a target runtime is degraded; recover or "
                "restart it before swapping",
                targets=len(targets),
            )

        if hasattr(targets[0], "swap_prepare"):
            # Process mode: the targets are worker stubs; roll them via
            # the cross-process swap protocol and the pool's
            # shared-memory generations.
            return self._swap_remote(
                targets, model_path, runtime_config,
                version_before, new_version,
            )

        # Stage 1+2: load + prepare, entirely off the request path — the
        # old runtimes keep serving while this thread builds and warms.
        stage = "load"
        try:
            chaos_mod.maybe_fail(
                "serving.swap", stage="load", path=model_path
            )
            model, index_maps = ScoringRuntime.load_model(model_path)
            stage = "prepare"
            fresh = []
            for t in targets:
                cfg = runtime_config or t.runtime.config
                rt = ScoringRuntime(model, index_maps, cfg)
                rt.model_version = new_version
                rt.model_path = model_path
                margins, means = rt.score_rows([rt.probe_row()])
                if not (
                    np.isfinite(margins).all() and np.isfinite(means).all()
                ):
                    raise ValueError(
                        "pre-commit verification probe returned "
                        "non-finite scores"
                    )
                fresh.append(rt)
            chaos_mod.maybe_fail("serving.swap", stage="prepare")
        except Exception as exc:  # noqa: BLE001 — abort, old version serves
            return self._rolled_back(
                version_before, model_path, stage,
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
            )

        # Stage 3: atomic commit (attribute assignment per target).
        previous = [(t, t.runtime) for t in targets]
        for t, rt in zip(targets, fresh):
            t.runtime = rt

        # Stage 4: verify through the real dispatch path; any failure
        # restores the previous runtimes.
        try:
            chaos_mod.maybe_fail("serving.swap", stage="verify")
            for t, rt in zip(targets, fresh):
                fut = t.submit(rt.probe_row(), bypass_admission=True)
                result = fut.result(timeout=self.probe_timeout_s)
                if not np.isfinite(result["score"]):
                    raise ValueError(
                        "post-swap probe returned a non-finite score"
                    )
        except Exception as exc:  # noqa: BLE001 — roll back, then report
            for t, old in previous:
                t.runtime = old
            return self._rolled_back(
                version_before, model_path, "verify",
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
            )

        self.version = new_version
        self._max_version = new_version
        self.model_path = model_path
        self._previous = previous
        self._remote_previous = None
        self.swaps += 1
        tel.counter("serving_swaps_total").inc()
        tel.gauge("serving_model_version").set(new_version)
        tel.event(
            "serving.swap",
            version_before=version_before,
            version_after=new_version,
            model_path=model_path,
            targets=len(targets),
        )
        if self._on_commit is not None:
            sample = fresh[0]
            self._on_commit(
                model, index_maps, sample.config, new_version, model_path
            )
        return SwapResult(
            status="swapped",
            version_before=version_before,
            version_after=new_version,
            model_path=model_path,
            targets=len(targets),
        )

    def _swap_remote(
        self,
        targets: list,
        model_path: str,
        runtime_config: Optional[RuntimeConfig],
        version_before: int,
        new_version: int,
        site: str = "serving.swap",
        preloaded: Optional[tuple] = None,
        carry_hot: bool = False,
        on_success: Optional[Callable] = None,
    ) -> SwapResult:
        """The four swap stages over the worker protocol.  Same chaos
        occurrences (load=0, prepare=1, verify=2) so every scripted
        FaultPlan written against in-process swaps scripts this path
        identically.

        The delta path (:meth:`swap_delta`) rides this same machinery
        with ``site="publish.apply"``, a ``preloaded`` (model,
        index_maps) it already patched parent-side (its "load" stage —
        chaos occurrence 0 — already fired there), and
        ``carry_hot=True`` so each worker clones its compiled kernels
        and hot sets instead of rebuilding cold."""
        tel = telemetry_mod.current()
        pool = targets[0].pool
        generation = None
        prepared: list = []
        stage = "load"
        try:
            if preloaded is not None:
                model, index_maps = preloaded
            else:
                chaos_mod.maybe_fail(site, stage="load", path=model_path)
                model, index_maps = ScoringRuntime.load_model(model_path)
            # ONE shared-memory publication for the whole pool; workers
            # attach it zero-copy during prepare.
            generation = pool.publish(
                model, index_maps, version=new_version, path=model_path
            )
            stage = "prepare"
            for t in targets:
                t.swap_prepare(
                    generation.manifest, runtime_config,
                    carry_hot=carry_hot,
                )
                prepared.append(t)
            chaos_mod.maybe_fail(site, stage="prepare")
        except Exception as exc:  # noqa: BLE001 — abort, old version serves
            for t in prepared:
                t.swap_abort(new_version)
            if generation is not None:
                pool.retire_generation(generation)
            return self._rolled_back(
                version_before, model_path, stage,
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
            )

        committed: list = []
        try:
            for t in targets:
                t.swap_commit(new_version)
                committed.append(t)
            chaos_mod.maybe_fail(site, stage="verify")
            for t in targets:
                fut = t.submit(
                    generation.parser.probe_row(), bypass_admission=True
                )
                result = fut.result(timeout=self.probe_timeout_s)
                if not np.isfinite(result["score"]):
                    raise ValueError(
                        "post-swap probe returned a non-finite score"
                    )
        except Exception as exc:  # noqa: BLE001 — roll back, then report
            for t in committed:
                try:
                    t.swap_rollback()
                except Exception:  # noqa: BLE001 — dead worker respawns
                    pass           # on the still-current old generation
            for t in targets:
                if t not in committed:
                    t.swap_abort(new_version)
            pool.retire_generation(generation)
            return self._rolled_back(
                version_before, model_path, "verify",
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
            )

        # Promote: new generation becomes what restarts attach; the old
        # one stays linked as the rollback window.
        pool.commit_generation(generation)
        self.version = new_version
        self._max_version = new_version
        self.model_path = model_path
        self._previous = []
        self._remote_previous = (pool, version_before)
        self.swaps += 1
        tel.counter("serving_swaps_total").inc()
        tel.gauge("serving_model_version").set(new_version)
        tel.event(
            "serving.swap",
            version_before=version_before,
            version_after=new_version,
            model_path=model_path,
            targets=len(targets),
            mode="process" if preloaded is None else "process-delta",
        )
        if on_success is not None:
            on_success()
        if self._on_commit is not None:
            self._on_commit(
                model, index_maps,
                runtime_config or pool.runtime_config,
                new_version, model_path,
            )
        return SwapResult(
            status="swapped",
            version_before=version_before,
            version_after=new_version,
            model_path=model_path,
            targets=len(targets),
        )

    # -- tenant-scoped swaps (serving/tenancy.py) ----------------------------
    def _tenant_version_before(self, tenant: str) -> int:
        entry = self._tenant_versions.get(tenant)
        return entry[0] if entry is not None else self.version

    def _swap_tenant_locked(
        self,
        tenant: str,
        model_path: str,
        runtime_config: Optional[RuntimeConfig],
    ) -> SwapResult:
        """Roll ONE tenant's route onto a new model version.

        Same four stages and chaos occurrences as a full swap
        (``serving.swap`` load=0 / prepare=1 / verify=2), but commit is
        ``set_tenant_route`` per target instead of the ``runtime``
        assignment — the default route keeps serving everyone else
        untouched, and ``self.version`` does not move (only
        ``_max_version``, keeping the version sequence monotone)."""
        tel = telemetry_mod.current()
        version_before = self._tenant_version_before(tenant)
        new_version = self._max_version + 1
        targets = list(self._targets_fn())
        if not targets:
            return self._rolled_back(
                version_before, model_path, "load",
                "no live targets to swap", 0, tenant=tenant,
            )
        if any(getattr(t.runtime, "degraded", False) for t in targets):
            self.deferred += 1
            tel.counter("serving_swaps_deferred_total").inc()
            tel.event(
                "serving.swap_deferred",
                model_path=model_path,
                version=version_before,
                tenant=tenant,
            )
            return SwapResult(
                status="deferred",
                version_before=version_before,
                version_after=version_before,
                model_path=model_path,
                stage="load",
                reason="a target runtime is degraded; recover or "
                "restart it before swapping",
                targets=len(targets),
                tenant=tenant,
            )

        if hasattr(targets[0], "swap_prepare"):
            return self._swap_tenant_remote(
                tenant, targets, model_path, runtime_config,
                version_before, new_version,
            )

        stage = "load"
        try:
            chaos_mod.maybe_fail(
                "serving.swap", stage="load", path=model_path
            )
            model, index_maps = ScoringRuntime.load_model(model_path)
            stage = "prepare"
            fresh = []
            for t in targets:
                cfg = runtime_config or t.runtime.config
                rt = ScoringRuntime(model, index_maps, cfg)
                rt.model_version = new_version
                rt.model_path = model_path
                margins, means = rt.score_rows([rt.probe_row()])
                if not (
                    np.isfinite(margins).all() and np.isfinite(means).all()
                ):
                    raise ValueError(
                        "pre-commit verification probe returned "
                        "non-finite scores"
                    )
                fresh.append(rt)
            chaos_mod.maybe_fail("serving.swap", stage="prepare")
        except Exception as exc:  # noqa: BLE001 — abort, old route serves
            return self._rolled_back(
                version_before, model_path, stage,
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
                tenant=tenant,
            )

        # Commit: route the tenant, keep the default runtime in place.
        previous_routes = [(t, t.tenant_route(tenant)) for t in targets]
        for t, rt in zip(targets, fresh):
            t.set_tenant_route(tenant, rt)

        # Verify THROUGH the dispatch path: a probe row carrying the
        # tenant id must come back finite from the new route.
        try:
            chaos_mod.maybe_fail("serving.swap", stage="verify")
            for t, rt in zip(targets, fresh):
                probe = rt.probe_row()
                probe.tenant = tenant
                fut = t.submit(probe, bypass_admission=True)
                result = fut.result(timeout=self.probe_timeout_s)
                if not np.isfinite(result["score"]):
                    raise ValueError(
                        "post-swap probe returned a non-finite score"
                    )
        except Exception as exc:  # noqa: BLE001 — roll back, then report
            for t, old in previous_routes:
                if old is None:
                    t.clear_tenant_route(tenant)
                else:
                    t.set_tenant_route(tenant, old)
            return self._rolled_back(
                version_before, model_path, "verify",
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
                tenant=tenant,
            )

        prev_entry = self._tenant_versions.get(tenant)
        self._tenant_versions[tenant] = (new_version, model_path)
        self._max_version = new_version
        self._tenant_previous = (
            "thread", tenant, previous_routes, prev_entry
        )
        self.swaps += 1
        tel.counter("serving_swaps_total").inc()
        tel.gauge(
            f"serving_tenant_{tenant_slug(tenant)}_model_version"
        ).set(new_version)
        tel.event(
            "serving.swap",
            version_before=version_before,
            version_after=new_version,
            model_path=model_path,
            targets=len(targets),
            tenant=tenant,
        )
        if self._on_tenant_commit is not None:
            sample = fresh[0]
            self._on_tenant_commit(
                tenant, model, index_maps, sample.config,
                new_version, model_path,
            )
        return SwapResult(
            status="swapped",
            version_before=version_before,
            version_after=new_version,
            model_path=model_path,
            targets=len(targets),
            tenant=tenant,
        )

    def _swap_tenant_remote(
        self,
        tenant: str,
        targets: list,
        model_path: str,
        runtime_config: Optional[RuntimeConfig],
        version_before: int,
        new_version: int,
    ) -> SwapResult:
        """Tenant swap over the worker protocol: one shared-memory
        publication, per-worker prepare, then a tenant-tagged
        ``swap_commit`` — each worker routes the tenant onto the
        attached runtime without touching its default.  Success records
        the generation in the pool's TENANT registry (never the default
        generation window), so respawned workers replay the route."""
        tel = telemetry_mod.current()
        pool = targets[0].pool
        generation = None
        prepared: list = []
        stage = "load"
        try:
            chaos_mod.maybe_fail(
                "serving.swap", stage="load", path=model_path
            )
            model, index_maps = ScoringRuntime.load_model(model_path)
            generation = pool.publish(
                model, index_maps, version=new_version, path=model_path
            )
            generation.runtime_config = runtime_config
            stage = "prepare"
            for t in targets:
                t.swap_prepare(generation.manifest, runtime_config)
                prepared.append(t)
            chaos_mod.maybe_fail("serving.swap", stage="prepare")
        except Exception as exc:  # noqa: BLE001 — abort, old route serves
            for t in prepared:
                t.swap_abort(new_version)
            if generation is not None:
                pool.retire_generation(generation)
            return self._rolled_back(
                version_before, model_path, stage,
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
                tenant=tenant,
            )

        committed: list = []
        try:
            for t in targets:
                t.swap_commit(new_version, tenant=tenant)
                committed.append(t)
            chaos_mod.maybe_fail("serving.swap", stage="verify")
            for t in targets:
                probe = generation.parser.probe_row()
                probe.tenant = tenant
                fut = t.submit(probe, bypass_admission=True)
                result = fut.result(timeout=self.probe_timeout_s)
                if not np.isfinite(result["score"]):
                    raise ValueError(
                        "post-swap probe returned a non-finite score"
                    )
        except Exception as exc:  # noqa: BLE001 — roll back, then report
            for t in committed:
                try:
                    t.swap_rollback(tenant=tenant)
                except Exception:  # noqa: BLE001 — dead worker respawns
                    pass           # without the uncommitted route
            for t in targets:
                if t not in committed:
                    t.swap_abort(new_version)
            pool.retire_generation(generation)
            return self._rolled_back(
                version_before, model_path, "verify",
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
                tenant=tenant,
            )

        pool.commit_tenant_generation(tenant, generation)
        prev_entry = self._tenant_versions.get(tenant)
        self._tenant_versions[tenant] = (new_version, model_path)
        self._max_version = new_version
        self._tenant_previous = ("process", tenant, pool, prev_entry)
        self.swaps += 1
        tel.counter("serving_swaps_total").inc()
        tel.gauge(
            f"serving_tenant_{tenant_slug(tenant)}_model_version"
        ).set(new_version)
        tel.event(
            "serving.swap",
            version_before=version_before,
            version_after=new_version,
            model_path=model_path,
            targets=len(targets),
            tenant=tenant,
            mode="process",
        )
        if self._on_tenant_commit is not None:
            self._on_tenant_commit(
                tenant, model, index_maps,
                runtime_config or pool.runtime_config,
                new_version, model_path,
            )
        return SwapResult(
            status="swapped",
            version_before=version_before,
            version_after=new_version,
            model_path=model_path,
            targets=len(targets),
            tenant=tenant,
        )

    # -- the delta path ------------------------------------------------------
    def swap_delta(
        self,
        delta_path: str,
        runtime_config: Optional[RuntimeConfig] = None,
    ) -> SwapResult:
        """Roll every live target onto ``delta_path`` — a delta artifact
        (``freshness/delta.py``), not a model directory — by patching
        only the changed rows of the currently-serving model.

        Same contract and state machine as :meth:`swap`: serialized,
        versioned on the same monotone registry (so one-step
        :meth:`rollback` after a delta apply restores the pre-delta
        version exactly like after a full swap), deferred while any
        target is degraded, and never raises for a failed apply — a
        torn/tampered artifact or a base-mismatch refusal comes back as
        status ``"rolled_back"`` with the pointed reason, the old
        version still serving.  Chaos site ``publish.apply`` fires at
        stages ``load``/``prepare``/``verify`` (occurrences 0/1/2),
        mirroring ``serving.swap``.

        In-process targets are cloned via
        :meth:`ScoringRuntime.patched` — shared compiled kernels, hot
        sets carried and rebuilt from the patched model — so the apply
        wall is row-patching, not a cold rebuild.  Process workers ride
        the same swap protocol with a ``carry_hot`` prepare: the parent
        patches its host-side copy, publishes ONE new shared-memory
        generation, and each worker clones its own runtime around the
        attached tables."""
        if not self._swap_lock.acquire(blocking=False):
            raise SwapInProgressError(
                "a model swap is already in progress; retry after it "
                "completes"
            )
        try:
            self.in_progress = True
            return self._swap_delta_locked(delta_path, runtime_config)
        finally:
            self.in_progress = False
            self._swap_lock.release()

    def _swap_delta_locked(
        self, delta_path: str, runtime_config: Optional[RuntimeConfig]
    ) -> SwapResult:
        # Runtime import: freshness imports serving for its applier, so
        # a module-level import here would be circular.
        from photon_ml_tpu.freshness.delta import apply_delta, read_delta

        tel = telemetry_mod.current()
        version_before = self.version
        new_version = self._max_version + 1
        targets = list(self._targets_fn())
        if not targets:
            return self._rolled_back(
                version_before, delta_path, "load",
                "no live targets to apply the delta to", 0,
            )
        if any(getattr(t.runtime, "degraded", False) for t in targets):
            self.deferred += 1
            tel.counter("serving_swaps_deferred_total").inc()
            tel.event(
                "serving.swap_deferred",
                model_path=delta_path,
                version=version_before,
                mode="delta",
            )
            return SwapResult(
                status="deferred",
                version_before=version_before,
                version_after=version_before,
                model_path=delta_path,
                stage="load",
                reason="a target runtime is degraded; recover or "
                "restart it before applying a delta",
                targets=len(targets),
            )

        if hasattr(targets[0], "swap_prepare"):
            # Process mode: patch the parent's host-side copy of the
            # serving model, then roll the patched model through the
            # shared swap protocol as a new shm generation.
            stage = "load"
            try:
                chaos_mod.maybe_fail(
                    "publish.apply", stage="load", path=delta_path
                )
                pool = targets[0].pool
                base_model, index_maps = pool.current_model()
                delta = read_delta(delta_path)
                model = apply_delta(base_model, delta)
            except Exception as exc:  # noqa: BLE001 — refuse, old serves
                return self._rolled_back(
                    version_before, delta_path, stage,
                    f"{type(exc).__name__}: {exc}"[:300], len(targets),
                )
            return self._swap_remote(
                targets, delta_path, runtime_config,
                version_before, new_version,
                site="publish.apply",
                preloaded=(model, index_maps),
                carry_hot=True,
                on_success=lambda: self._record_freshness(
                    delta, new_version, len(targets)
                ),
            )

        stage = "load"
        try:
            chaos_mod.maybe_fail(
                "publish.apply", stage="load", path=delta_path
            )
            delta = read_delta(delta_path)
            # Replicas restarted through a factory hold DISTINCT (but
            # bitwise-equal) model objects; patch once per distinct base
            # and let apply_delta's whole-base checksum verification
            # refuse any target that ACTUALLY diverged — that comes back
            # as a rolled_back with the pointed base-mismatch reason.
            patched_by_base: dict = {}
            for t in targets:
                key = id(t.runtime.model)
                if key not in patched_by_base:
                    patched_by_base[key] = apply_delta(
                        t.runtime.model, delta
                    )
            model = patched_by_base[id(targets[0].runtime.model)]
            index_maps = targets[0].runtime.index_maps
            stage = "prepare"
            fresh = []
            for t in targets:
                cfg = runtime_config or t.runtime.config
                rt = ScoringRuntime.patched(
                    t.runtime,
                    patched_by_base[id(t.runtime.model)],
                    t.runtime.index_maps,
                    cfg,
                )
                rt.model_version = new_version
                rt.model_path = delta_path
                margins, means = rt.score_rows([rt.probe_row()])
                if not (
                    np.isfinite(margins).all() and np.isfinite(means).all()
                ):
                    raise ValueError(
                        "pre-commit verification probe returned "
                        "non-finite scores"
                    )
                fresh.append(rt)
            chaos_mod.maybe_fail("publish.apply", stage="prepare")
        except Exception as exc:  # noqa: BLE001 — refuse, old serves
            return self._rolled_back(
                version_before, delta_path, stage,
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
            )

        # Atomic commit + through-the-dispatch-path verify: identical
        # discipline to the full swap.
        previous = [(t, t.runtime) for t in targets]
        for t, rt in zip(targets, fresh):
            t.runtime = rt
        try:
            chaos_mod.maybe_fail("publish.apply", stage="verify")
            for t, rt in zip(targets, fresh):
                fut = t.submit(rt.probe_row(), bypass_admission=True)
                result = fut.result(timeout=self.probe_timeout_s)
                if not np.isfinite(result["score"]):
                    raise ValueError(
                        "post-apply probe returned a non-finite score"
                    )
        except Exception as exc:  # noqa: BLE001 — roll back, then report
            for t, old in previous:
                t.runtime = old
            return self._rolled_back(
                version_before, delta_path, "verify",
                f"{type(exc).__name__}: {exc}"[:300], len(targets),
            )

        self.version = new_version
        self._max_version = new_version
        self.model_path = delta_path
        self._previous = previous
        self._remote_previous = None
        self.swaps += 1
        tel.counter("serving_swaps_total").inc()
        tel.gauge("serving_model_version").set(new_version)
        tel.event(
            "serving.swap",
            version_before=version_before,
            version_after=new_version,
            model_path=delta_path,
            targets=len(targets),
            mode="delta",
        )
        self._record_freshness(delta, new_version, len(targets))
        if self._on_commit is not None:
            sample = fresh[0]
            self._on_commit(
                model, index_maps, sample.config, new_version, delta_path
            )
        return SwapResult(
            status="swapped",
            version_before=version_before,
            version_after=new_version,
            model_path=delta_path,
            targets=len(targets),
        )

    def _record_freshness(
        self, delta, new_version: int, targets: int
    ) -> None:
        """Delta-apply observability: the moment a delta commits, its
        newest absorbed event is SERVABLE — the event→servable histogram
        is the freshness SLO (docs/freshness.md)."""
        tel = telemetry_mod.current()
        tel.counter("freshness_deltas_applied_total").inc()
        tel.counter("freshness_delta_rows").inc(delta.n_changed_rows)
        tel.gauge("freshness_applied_version").set(new_version)
        if delta.event_wall_epoch is not None:
            import time

            now_wall = time.time()
            tel.histogram("freshness_event_to_servable_seconds").observe(
                max(0.0, now_wall - delta.event_wall_epoch)
            )
        tel.event(
            "freshness.delta_applied",
            version=new_version,
            rows=delta.n_changed_rows,
            targets=targets,
        )

    def _rolled_back(
        self,
        version_before: int,
        model_path: str,
        stage: str,
        reason: str,
        targets: int,
        tenant: Optional[str] = None,
    ) -> SwapResult:
        """Record an aborted (pre-commit) or rolled-back (post-commit)
        swap; either way the previous version is the one serving."""
        tel = telemetry_mod.current()
        self.rollbacks += 1
        tel.counter("serving_rollbacks_total").inc()
        tel.event(
            "serving.rollback",
            stage=stage,
            reason=reason,
            model_path=model_path,
            version=version_before,
            tenant=tenant,
        )
        return SwapResult(
            status="rolled_back",
            version_before=version_before,
            version_after=version_before,
            model_path=model_path,
            stage=stage,
            reason=reason,
            targets=targets,
            tenant=tenant,
        )

    def rollback(self, tenant: Optional[str] = None) -> SwapResult:
        """One-step manual rollback to the version the last successful
        swap replaced.  The retained runtimes (warm hot sets and all)
        are restored on their original targets.  With ``tenant`` set,
        only that tenant's route rolls back — to its previous version,
        or onto the default route if the undone swap was its first."""
        if not self._swap_lock.acquire(blocking=False):
            raise SwapInProgressError(
                "a model swap is in progress; retry after it completes"
            )
        try:
            self.in_progress = True
            if tenant is not None:
                return self._rollback_tenant(tenant)
            if self._remote_previous is not None:
                return self._rollback_remote()
            if not self._previous:
                return SwapResult(
                    status="rolled_back",
                    version_before=self.version,
                    version_after=self.version,
                    model_path=self.model_path,
                    stage="load",
                    reason="nothing to roll back to (no prior "
                    "successful swap retained)",
                )
            version_before = self.version
            for t, old in self._previous:
                t.runtime = old
            restored = self._previous[0][1]
            self._previous = []
            self.version = restored.model_version
            self.model_path = restored.model_path
            self.rollbacks += 1
            tel = telemetry_mod.current()
            tel.counter("serving_rollbacks_total").inc()
            tel.gauge("serving_model_version").set(self.version)
            tel.event(
                "serving.rollback",
                stage="manual",
                reason="operator-requested rollback",
                model_path=self.model_path,
                version=self.version,
            )
            if self._on_commit is not None:
                self._on_commit(
                    restored.model, restored.index_maps, restored.config,
                    restored.model_version, restored.model_path,
                )
            return SwapResult(
                status="rolled_back",
                version_before=version_before,
                version_after=self.version,
                model_path=self.model_path,
                stage="manual",
                reason="operator-requested rollback",
                targets=len(self._targets_fn()),
            )
        finally:
            self.in_progress = False
            self._swap_lock.release()

    def _rollback_tenant(self, tenant: str) -> SwapResult:
        """One-step rollback of a tenant route (thread or process
        mode).  Restores the route the last tenant swap displaced —
        or clears it, putting the tenant back on the default route —
        and re-syncs the version registry.  A worker that holds no
        retained previous route (restarted after the tenant commit) is
        converge-killed, exactly like the default-route remote
        rollback."""
        tel = telemetry_mod.current()
        token = self._tenant_previous
        version_before = self._tenant_version_before(tenant)
        if token is None or token[1] != tenant:
            return SwapResult(
                status="rolled_back",
                version_before=version_before,
                version_after=version_before,
                model_path=self.model_path,
                stage="load",
                reason=f"nothing to roll back for tenant {tenant!r} "
                "(no prior tenant swap retained)",
                tenant=tenant,
            )
        mode, _, carrier, prev_entry = token
        restored_runtime = None
        if mode == "thread":
            for t, old in carrier:
                if old is None:
                    t.clear_tenant_route(tenant)
                else:
                    t.set_tenant_route(tenant, old)
                    restored_runtime = old
        else:
            pool = carrier
            targets = list(self._targets_fn())
            stale: list = []
            for t in targets:
                try:
                    if not t.swap_rollback(tenant=tenant):
                        stale.append(t)
                except Exception:  # noqa: BLE001 — a dead worker
                    pass           # respawns on the restored registry
            pool.rollback_tenant_generation(tenant)
            for t in stale:
                reason = (
                    f"no retained previous route for tenant {tenant!r}; "
                    "respawn replays the restored tenant registry"
                )
                if self._on_kill is not None:
                    self._on_kill(t, reason)
                else:
                    t.kill(reason)
        if prev_entry is None:
            self._tenant_versions.pop(tenant, None)
            version_after = self.version
            restored_path = self.model_path
        else:
            self._tenant_versions[tenant] = prev_entry
            version_after, restored_path = prev_entry
        self._tenant_previous = None
        self.rollbacks += 1
        tel.counter("serving_rollbacks_total").inc()
        tel.gauge(
            f"serving_tenant_{tenant_slug(tenant)}_model_version"
        ).set(version_after)
        tel.event(
            "serving.rollback",
            stage="manual",
            reason="operator-requested tenant rollback",
            model_path=restored_path,
            version=version_after,
            tenant=tenant,
        )
        if self._on_tenant_commit is not None:
            if restored_runtime is not None:
                self._on_tenant_commit(
                    tenant,
                    restored_runtime.model,
                    restored_runtime.index_maps,
                    restored_runtime.config,
                    restored_runtime.model_version,
                    restored_runtime.model_path,
                )
            elif mode == "thread":
                # Back on the default route: clear any retained factory.
                self._on_tenant_commit(tenant, None, None, None, None, None)
        return SwapResult(
            status="rolled_back",
            version_before=version_before,
            version_after=version_after,
            model_path=restored_path,
            stage="manual",
            reason="operator-requested tenant rollback",
            targets=len(self._targets_fn()),
            tenant=tenant,
        )

    def _rollback_remote(self) -> SwapResult:
        """Process-mode manual rollback: each worker restores its
        retained previous runtime, then the pool drops the rolled-back
        generation so restarts attach the restored one.  (No
        ``on_commit`` call — the supervisor's commit hook is a no-op in
        pool mode, and the restored model object lives only in the
        workers.)"""
        pool, _ = self._remote_previous
        self._remote_previous = None
        version_before = self.version
        targets = list(self._targets_fn())
        stale: list = []
        for t in targets:
            try:
                if not t.swap_rollback():
                    # Restarted after the commit: no worker-side
                    # previous to restore.  Converge it below.
                    stale.append(t)
            except Exception:  # noqa: BLE001 — a dead worker respawns
                pass           # on the restored generation below
        restored = pool.rollback_generation()
        for t in stale:
            reason = "no retained previous; respawn on restored generation"
            if self._on_kill is not None:
                self._on_kill(t, reason)
            else:
                t.kill(reason)
        self.version = restored.version
        self.model_path = restored.path
        self.rollbacks += 1
        tel = telemetry_mod.current()
        tel.counter("serving_rollbacks_total").inc()
        tel.gauge("serving_model_version").set(self.version)
        tel.event(
            "serving.rollback",
            stage="manual",
            reason="operator-requested rollback",
            model_path=self.model_path,
            version=self.version,
            mode="process",
        )
        return SwapResult(
            status="rolled_back",
            version_before=version_before,
            version_after=self.version,
            model_path=self.model_path,
            stage="manual",
            reason="operator-requested rollback",
            targets=len(targets),
        )
