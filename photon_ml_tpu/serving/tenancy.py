"""Tenancy primitives: per-tenant isolation for the serving stack.

ROADMAP item 3's multi-tenant half, in the hierarchical-isolation shape
of Snap ML (arXiv:1803.06333): every level of the serving stack gets a
per-tenant boundary so overload and failure are contained where they
originate instead of shed onto neighbors.

- :class:`TenantSpec` / :class:`TenancyConfig` — the declarative
  contract per tenant: token-bucket quota, bulkhead queue partition,
  tiered-admission watermarks, p99 SLO, and circuit-breaker knobs.
  Frozen and picklable: the config rides ``BatcherConfig`` into spawned
  worker processes unchanged, so thread- and process-mode admission run
  the SAME policy (serving/worker.py).
- :class:`TokenBucket` — the quota primitive: refill at ``rate_rps``
  up to ``burst``, one token per admitted request.  ``rate_rps=None``
  is unlimited; ``rate_rps=0`` admits nothing (a suspended tenant).
  NOT internally locked — the batcher mutates it under its tenancy
  lock; the injectable clock keeps tests sleep-free (the same
  discipline as chaos/breaker.py).
- :class:`TenantRouter` — the tenant → model-version view on top of
  the :class:`~photon_ml_tpu.serving.swap.HotSwapper` monotone version
  registry: per-tenant hot swap and one-step rollback, with unknown
  tenants following the default route (the swapper's ``version``).

The enforcement half — bulkhead partitions, per-tenant admission tiers,
per-tenant breakers, tenant-routed dispatch, and the per-tenant
``serving_tenant_<t>_request_latency_seconds`` metric family — lives in
``serving/batcher.py``; the chaos seam is ``serving.tenant``
(docs/robustness.md), and the proof is the ``noisy_neighbor`` loadgen
scenario (serving/loadgen.py): an aggressor at 10x quota sheds only its
own traffic while a victim's p99 holds inside its SLO with zero failed
requests.
"""

from __future__ import annotations

import dataclasses
import re
import time
from typing import Callable, Optional

#: metric-family component derived from a tenant name; anything outside
#: [a-z0-9_] folds to "_" so dynamic names stay convention-shaped
#: (<subsystem>_<name>_<unit>, docs/telemetry.md).
_SLUG_RE = re.compile(r"[^a-z0-9_]+")


def tenant_slug(name: str) -> str:
    """Sanitize a tenant name into a metric-name component."""
    slug = _SLUG_RE.sub("_", str(name).lower()).strip("_")
    return slug or "tenant"


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's isolation contract (all enforcement is per
    MicroBatcher — i.e. per replica/worker; a pool of N replicas gives
    the tenant ~N× these budgets in aggregate, see docs/serving.md)."""

    name: str
    #: sustained admission rate (token-bucket refill).  None = no quota;
    #: 0.0 = zero-quota tenant, every non-probe request is shed.
    quota_rps: Optional[float] = None
    #: bucket capacity in tokens (how big a burst admits at once);
    #: defaults to max(quota_rps, 1).
    burst: Optional[float] = None
    #: bulkhead partition depth: the most rows this tenant may hold
    #: queued in one batcher.  Its burst fills THIS, never a
    #: neighbor's share of the queue.
    max_queue: int = 64
    #: partition-depth fraction where tier 1 (shed low-priority /
    #: over-deadline rows) engages for this tenant alone.
    shed_watermark: float = 0.5
    #: partition-depth fraction where tier 2 (reject everything but
    #: probes) engages for this tenant alone.
    reject_watermark: float = 0.9
    #: per-tenant latency SLO: an observed per-tenant p99 above this
    #: escalates THIS tenant's admission to at least tier 1.
    p99_slo_ms: Optional[float] = None
    #: circuit-breaker knobs (chaos/breaker.py): consecutive scoring
    #: failures on this tenant's model path trip the breaker, and the
    #: tenant degrades alone while the cooldown runs.
    breaker_cooldown_s: float = 5.0
    breaker_failure_threshold: int = 3

    def __post_init__(self):
        if not str(self.name):
            raise ValueError("tenant name must be non-empty")
        if self.quota_rps is not None and self.quota_rps < 0:
            raise ValueError(
                f"quota_rps must be >= 0 or None, got {self.quota_rps}"
            )
        if self.burst is not None and self.burst <= 0:
            raise ValueError(f"burst must be > 0, got {self.burst}")
        if self.max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {self.max_queue}")
        if not (0.0 < self.shed_watermark <= self.reject_watermark <= 1.0):
            raise ValueError(
                "need 0 < shed_watermark <= reject_watermark <= 1, got "
                f"{self.shed_watermark} / {self.reject_watermark}"
            )
        if self.breaker_cooldown_s < 0:
            raise ValueError(
                f"breaker_cooldown_s must be >= 0, got "
                f"{self.breaker_cooldown_s}"
            )
        if self.breaker_failure_threshold < 1:
            raise ValueError(
                f"breaker_failure_threshold must be >= 1, got "
                f"{self.breaker_failure_threshold}"
            )

    @property
    def slug(self) -> str:
        return tenant_slug(self.name)

    @property
    def effective_burst(self) -> float:
        if self.burst is not None:
            return float(self.burst)
        if self.quota_rps is None:
            return 1.0  # unused: no quota means no bucket draw
        return max(float(self.quota_rps), 1.0)


@dataclasses.dataclass(frozen=True)
class TenancyConfig:
    """The full tenancy policy one serving unit enforces.

    ``tenants`` declares the named tenants (each with its own bulkhead
    partition, quota, tiers, SLO, and breaker); every request whose
    tenant id is unknown — or absent — shares the ``default`` spec's
    partition and budgets, so an unregistered tenant can never starve a
    registered one."""

    tenants: tuple = ()
    default: TenantSpec = dataclasses.field(
        default_factory=lambda: TenantSpec(name="default")
    )

    def __post_init__(self):
        names = [t.name for t in self.tenants]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate tenant names: {names}")
        slugs = [t.slug for t in self.tenants] + [self.default.slug]
        if len(set(slugs)) != len(slugs):
            raise ValueError(
                f"tenant names collide after metric-slug folding: {slugs}"
            )

    def spec_for(self, tenant: Optional[str]) -> TenantSpec:
        """The governing spec: the named tenant's, else the default."""
        if tenant is not None:
            for t in self.tenants:
                if t.name == tenant:
                    return t
        return self.default

    def is_known(self, tenant: Optional[str]) -> bool:
        return any(t.name == tenant for t in self.tenants)

    @property
    def partition_total(self) -> int:
        """Aggregate bulkhead capacity — what the physical queue must
        hold so no tenant's burst can consume a neighbor's slots."""
        return sum(t.max_queue for t in self.tenants) + self.default.max_queue


class TokenBucket:
    """Classic token bucket with injectable clock; caller-locked."""

    def __init__(
        self,
        rate_rps: Optional[float],
        burst: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.rate_rps = rate_rps
        self.burst = float(burst)
        self._clock = clock
        self._tokens = self.burst
        self._refill_t = clock()
        self.admitted = 0
        self.denied = 0

    def try_acquire(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; never blocks."""
        if self.rate_rps is None:
            self.admitted += 1
            return True
        if self.rate_rps <= 0:
            # Zero-quota (suspended) tenant: nothing admits, not even
            # the initial burst fill.
            self.denied += 1
            return False
        now = self._clock()
        elapsed = max(0.0, now - self._refill_t)
        self._refill_t = now
        self._tokens = min(self.burst, self._tokens + elapsed * self.rate_rps)
        if self._tokens >= n:
            self._tokens -= n
            self.admitted += 1
            return True
        self.denied += 1
        return False

    @property
    def tokens(self) -> float:
        return self._tokens

    def reset_rate(
        self, rate_rps: Optional[float], burst: Optional[float] = None
    ) -> None:
        """Re-rate the bucket in place (quota lease updates,
        serving/fleet.py).  Tokens only ever CLAMP down to the new
        burst, never refill up — a lease shrink takes effect on the
        very next acquire, and a grow never mints admission credit the
        old rate did not earn."""
        if rate_rps is not None and rate_rps < 0:
            raise ValueError(f"rate_rps must be >= 0 or None, got {rate_rps}")
        if burst is not None:
            if burst <= 0:
                raise ValueError(f"burst must be > 0, got {burst}")
            self.burst = float(burst)
        self.rate_rps = rate_rps
        self._tokens = min(self._tokens, self.burst)

    def snapshot(self) -> dict:
        return {
            "rate_rps": self.rate_rps,
            "burst": self.burst,
            "tokens": round(self._tokens, 3),
            "admitted": self.admitted,
            "denied": self.denied,
        }


class TenantRouter:
    """Tenant → model version on top of the HotSwapper registry.

    The swapper owns the actual route state and the swap/rollback state
    machine (tenant swaps share its monotone version sequence and its
    serialization lock); this facade resolves a tenant id to the route
    that WILL score it — a tenant-scoped version when one was committed,
    else the default route every unknown tenant follows."""

    def __init__(self, swapper):
        self._swapper = swapper

    def route(self, tenant: Optional[str] = None) -> dict:
        routes = self._swapper.tenant_versions()
        if tenant is not None and tenant in routes:
            version, path = routes[tenant]
            return {
                "tenant": tenant, "version": version,
                "model_path": path, "default_route": False,
            }
        return {
            "tenant": tenant,
            "version": self._swapper.version,
            "model_path": self._swapper.model_path,
            "default_route": True,
        }

    def routes(self) -> dict:
        """Every committed tenant route plus the default."""
        out = {
            t: {"version": v, "model_path": p, "default_route": False}
            for t, (v, p) in self._swapper.tenant_versions().items()
        }
        out["*default*"] = {
            "version": self._swapper.version,
            "model_path": self._swapper.model_path,
            "default_route": True,
        }
        return out

    def swap(self, tenant: str, model_path: str, runtime_config=None):
        """Hot-swap ONE tenant onto a new model version; every other
        tenant's route (and the default) is untouched."""
        return self._swapper.swap(
            model_path, runtime_config, tenant=tenant
        )

    def rollback(self, tenant: str):
        """One-step rollback of a tenant route (back to its previous
        version, or to the default route if this was its first swap)."""
        return self._swapper.rollback(tenant=tenant)

    def stats(self) -> dict:
        return {"routes": self.routes()}
