"""Shared-memory model publication for process-level serving workers.

The parent process packs a :class:`~photon_ml_tpu.game.model.GameModel`
into ``multiprocessing.shared_memory`` segments exactly ONCE — one
segment per coordinate: the fixed-effect coefficient vector, or, for a
random-effect coordinate, the sorted entity-id blob plus CSR-style
``(cols, vals)`` coefficient rows — and hands workers a
sha256-fingerprinted **manifest** (segment names, array offsets,
per-segment digests, and a self-digest over the manifest body, riding
the PR-3 fingerprint-sidecar discipline).  Workers attach zero-copy:
every array the reconstructed model exposes is an ``np.frombuffer``
view into the mapped segment, so N workers pay ~1x (not Nx) the bytes
reported by the ``serving_shared_segment_bytes`` gauge.

Attach is verify-or-die (docs/serving.md "Process-level workers"): a
torn or tampered manifest, a missing segment, or a checksum mismatch
raises a pointed :class:`ModelMapError` and bumps
``model_map_unverified_total`` — never a silent partial map.

Segment lifecycle: the PARENT owns unlink.  :func:`publish_model`
creates segments (tracked in a module-level live set so leaks are
visible), :func:`unpublish_model` unlinks them; the worker pool keeps
the last TWO generations linked so a worker restarted inside a
swap/rollback window can still attach its pool's current manifest
(serving/procpool.py).  Workers attach and then *unregister* the
segment from their own ``resource_tracker`` — Python 3.10 registers
attached segments for cleanup, so without this a dying worker would
unlink shared state out from under its peers.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import multiprocessing
import os
import threading
from multiprocessing import resource_tracker, shared_memory
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import Coefficients, GeneralizedLinearModel

__all__ = [
    "MANIFEST_FORMAT",
    "ModelMapError",
    "ModelAttachment",
    "SharedEntityTable",
    "publish_model",
    "unpublish_model",
    "attach_model",
    "live_segments",
]

MANIFEST_FORMAT = "photon-shm-model-v1"

#: segment-internal arrays start on 8-byte boundaries (int64 offsets
#: must be aligned for zero-copy np.frombuffer views).
_ALIGN = 8

# Parent-side live-segment registry: name -> (handle, logical bytes).
# publish/unpublish keep it and the serving_shared_segment_bytes gauge
# in sync; tests and the process selfcheck assert it drains to empty.
_live_lock = threading.Lock()
_live: Dict[str, Tuple[shared_memory.SharedMemory, int]] = {}


class ModelMapError(RuntimeError):
    """A shared-memory model could not be verified at attach.

    Raised for a torn/tampered manifest, a missing or undersized
    segment, or a checksum mismatch — always BEFORE any partially
    mapped model is visible to the caller."""


def _unverified(message: str) -> None:
    telemetry_mod.current().counter("model_map_unverified_total").inc()
    raise ModelMapError(message)


def _manifest_digest(manifest: dict) -> str:
    """sha256 over the canonical JSON of everything but the self-digest
    field — torn writes and field tampering both change it."""
    body = {k: v for k, v in manifest.items() if k != "manifest_sha256"}
    return hashlib.sha256(
        json.dumps(body, sort_keys=True, default=str).encode("utf-8")
    ).hexdigest()


def _update_gauge() -> None:
    with _live_lock:
        total = sum(nbytes for _, nbytes in _live.values())
    telemetry_mod.current().gauge("serving_shared_segment_bytes").set(total)


def live_segments() -> List[str]:
    """Names of segments this process has published and not yet
    unlinked (diagnostic / leak-sentinel view)."""
    with _live_lock:
        return sorted(_live)


# -- packing (parent side) --------------------------------------------------
class _SegmentWriter:
    """Accumulates named arrays, then lays them into one shared-memory
    segment at aligned offsets and returns the per-array specs the
    manifest records."""

    def __init__(self) -> None:
        self._arrays: List[Tuple[str, np.ndarray]] = []

    def add(self, name: str, arr: np.ndarray) -> None:
        self._arrays.append((name, np.ascontiguousarray(arr)))

    def build(self) -> Tuple[shared_memory.SharedMemory, dict, int, str]:
        offsets = []
        cursor = 0
        for _, arr in self._arrays:
            cursor = (cursor + _ALIGN - 1) // _ALIGN * _ALIGN
            offsets.append(cursor)
            cursor += arr.nbytes
        nbytes = max(cursor, 1)  # SharedMemory size must be > 0
        shm = shared_memory.SharedMemory(create=True, size=nbytes)
        specs = {}
        for (name, arr), offset in zip(self._arrays, offsets):
            if arr.nbytes:
                dst = np.frombuffer(
                    shm.buf, dtype=arr.dtype, count=arr.size, offset=offset
                )
                dst[:] = arr.reshape(-1)
            specs[name] = {
                "offset": offset,
                "dtype": np.dtype(arr.dtype).str,
                "shape": [int(s) for s in arr.shape],
            }
        digest = hashlib.sha256(bytes(shm.buf[:nbytes])).hexdigest()
        return shm, specs, nbytes, digest


def _pack_random(sub: RandomEffectModel) -> Tuple[_SegmentWriter, dict]:
    # Sort by the ENCODED id (the attach-side binary search compares
    # utf-8 bytes); for str keys this equals Python's sort order.
    items = sorted(
        ((str(k).encode("utf-8"), k) for k in sub.coefficients),
        key=lambda kv: kv[0],
    )
    enc = [e for e, _ in items]
    blob = b"".join(enc)
    id_offsets = np.zeros(len(enc) + 1, np.int64)
    np.cumsum([len(e) for e in enc], out=id_offsets[1:])
    cols_parts: List[np.ndarray] = []
    vals_parts: List[np.ndarray] = []
    row_offsets = np.zeros(len(enc) + 1, np.int64)
    for i, (_, key) in enumerate(items):
        cols, vals = sub.coefficients[key]
        cols_parts.append(np.asarray(cols, np.int64).reshape(-1))
        vals_parts.append(np.asarray(vals, np.float32).reshape(-1))
        row_offsets[i + 1] = row_offsets[i] + cols_parts[-1].size
    w = _SegmentWriter()
    w.add("ids_blob", np.frombuffer(blob, np.uint8))
    w.add("id_offsets", id_offsets)
    w.add("row_offsets", row_offsets)
    w.add("cols", np.concatenate(cols_parts or [np.zeros(0, np.int64)]))
    w.add("vals", np.concatenate(vals_parts or [np.zeros(0, np.float32)]))
    extra = {
        "entity_key": sub.entity_key,
        "task": sub.task,
        "n_features": int(sub.n_features),
        "n_entities": len(enc),
    }
    return w, extra


def publish_model(
    model: GameModel, version: int = 1, path: Optional[str] = None
) -> dict:
    """Pack ``model`` into shared-memory segments and return the
    manifest workers attach with.  The caller (the worker pool) owns
    the segments' lifetime via :func:`unpublish_model`."""
    coordinates = []
    segments = {}
    created: List[shared_memory.SharedMemory] = []
    try:
        for name in sorted(model.models):
            sub = model.models[name]
            if isinstance(sub, RandomEffectModel):
                writer, extra = _pack_random(sub)
                kind = "random"
            elif isinstance(sub, FixedEffectModel):
                means = np.asarray(sub.model.coefficients.means, np.float32)
                writer = _SegmentWriter()
                writer.add("means", means)
                kind = "fixed"
                extra = {
                    "task": sub.model.task,
                    "n_features": int(means.shape[0]),
                }
            else:
                raise TypeError(f"unsupported coordinate type: {type(sub)}")
            shm, arrays, nbytes, digest = writer.build()
            created.append(shm)
            segments[shm.name] = {"nbytes": nbytes, "sha256": digest}
            coordinates.append({
                "name": name,
                "kind": kind,
                "feature_shard": sub.feature_shard,
                "segment": shm.name,
                "arrays": arrays,
                **extra,
            })
    except Exception:
        for shm in created:
            shm.close()
            shm.unlink()
        raise
    manifest = {
        "format": MANIFEST_FORMAT,
        "version": int(version),
        "path": path,
        "task": model.task,
        "publisher_pid": os.getpid(),
        "coordinates": coordinates,
        "segments": segments,
    }
    manifest["manifest_sha256"] = _manifest_digest(manifest)
    with _live_lock:
        for shm in created:
            _live[shm.name] = (shm, segments[shm.name]["nbytes"])
    _update_gauge()
    return manifest


def unpublish_model(manifest: dict) -> None:
    """Unlink the segments a manifest names (idempotent)."""
    for name in manifest.get("segments", {}):
        with _live_lock:
            entry = _live.pop(name, None)
        if entry is None:
            continue
        shm, _ = entry
        try:
            shm.close()
        except BufferError:
            pass  # a parent-side view still holds the buffer; unlink anyway
        try:
            shm.unlink()
        except FileNotFoundError:
            pass
    _update_gauge()


# -- attaching (worker side) ------------------------------------------------
class SharedEntityTable:
    """Read-only entity-id → ``(cols, vals)`` mapping over shared memory.

    Drop-in for ``RandomEffectModel.coefficients``: ``get`` /
    ``__getitem__`` / iteration / ``len`` are what the serving host
    path, :func:`~photon_ml_tpu.serving.kernels.dense_coefficient_rows`,
    and ``_ensure_packed`` use.  Lookups binary-search the sorted
    utf-8 id blob (O(log n) small decodes, no per-worker key dict) and
    return zero-copy ``np.frombuffer`` views of the row's columns and
    values."""

    __slots__ = ("_blob", "_id_offsets", "_row_offsets", "_cols", "_vals")

    def __init__(self, blob, id_offsets, row_offsets, cols, vals):
        self._blob = blob
        self._id_offsets = id_offsets
        self._row_offsets = row_offsets
        self._cols = cols
        self._vals = vals

    def __len__(self) -> int:
        return len(self._id_offsets) - 1

    def _id_bytes(self, i: int) -> bytes:
        return self._blob[self._id_offsets[i]:self._id_offsets[i + 1]].tobytes()

    def _rank(self, encoded: bytes) -> int:
        lo, hi = 0, len(self)
        while lo < hi:
            mid = (lo + hi) // 2
            if self._id_bytes(mid) < encoded:
                lo = mid + 1
            else:
                hi = mid
        return lo

    def get(self, key, default=None):
        encoded = str(key).encode("utf-8")
        i = self._rank(encoded)
        if i >= len(self) or self._id_bytes(i) != encoded:
            return default
        lo, hi = self._row_offsets[i], self._row_offsets[i + 1]
        return (self._cols[lo:hi], self._vals[lo:hi])

    def __getitem__(self, key):
        entry = self.get(key)
        if entry is None:
            raise KeyError(key)
        return entry

    def __contains__(self, key) -> bool:
        return self.get(key) is not None

    def __iter__(self) -> Iterator[str]:
        for i in range(len(self)):
            yield self._id_bytes(i).decode("utf-8")

    def keys(self) -> Iterator[str]:
        return iter(self)


@dataclasses.dataclass
class ModelAttachment:
    """Open handles on a mapped model's segments; the reconstructed
    model's arrays are views into these, so keep it alive as long as
    the model is in use and :meth:`close` it afterwards."""

    manifest: dict
    segments: Dict[str, shared_memory.SharedMemory]

    @property
    def nbytes(self) -> int:
        return sum(
            int(s["nbytes"]) for s in self.manifest["segments"].values()
        )

    def __enter__(self) -> "ModelAttachment":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def close(self) -> None:
        for shm in self.segments.values():
            try:
                shm.close()
            except BufferError:
                # A model view still references the buffer.  Unmapping
                # under a live view would be a use-after-free, so pin
                # the mapping for as long as the views need it (the
                # view chain keeps the mmap alive) and disarm
                # SharedMemory.__del__'s retry so shutdown isn't a wall
                # of "Exception ignored" tracebacks.  The fd can close
                # now — a POSIX mapping outlives its descriptor.
                shm._buf = None
                shm._mmap = None
                fd = getattr(shm, "_fd", -1)
                if fd >= 0:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                    shm._fd = -1
        self.segments = {}


def _attach_segment(
    name: str, spec: dict, publisher_pid: int
) -> shared_memory.SharedMemory:
    try:
        shm = shared_memory.SharedMemory(name=name)
    except FileNotFoundError:
        _unverified(
            f"shared segment {name!r} is gone (unlinked or never "
            "published) — the manifest is stale; re-fetch it from the pool"
        )
    if (
        os.getpid() != publisher_pid
        and multiprocessing.parent_process() is None
    ):
        # Python 3.10 registers ATTACHED segments with the resource
        # tracker; in a STANDALONE attaching process (own tracker) that
        # registration would unlink the segment out from under the
        # publisher when this process exits, so drop it.  A
        # multiprocessing child SHARES its parent's tracker daemon —
        # there the attach-register was a no-op on the already-present
        # entry, and unregistering would strip the publisher's own
        # registration (double-unregister KeyErrors at unlink, and no
        # crash cleanup).
        try:
            resource_tracker.unregister(shm._name, "shared_memory")
        except Exception:  # noqa: BLE001 — tracker internals vary
            pass
    nbytes = int(spec["nbytes"])
    if shm.size < nbytes:
        shm.close()
        _unverified(
            f"shared segment {name!r} is torn: {shm.size} bytes mapped, "
            f"manifest promises {nbytes}"
        )
    digest = hashlib.sha256(bytes(shm.buf[:nbytes])).hexdigest()
    if digest != spec["sha256"]:
        shm.close()
        _unverified(
            f"shared segment {name!r} failed checksum verification "
            f"(got {digest[:12]}…, manifest says "
            f"{str(spec['sha256'])[:12]}…) — refusing to map a "
            "corrupt model"
        )
    return shm


def _view(shm: shared_memory.SharedMemory, spec: dict) -> np.ndarray:
    dtype = np.dtype(spec["dtype"])
    shape = tuple(spec["shape"])
    count = int(np.prod(shape)) if shape else 1
    arr = np.frombuffer(
        shm.buf, dtype=dtype, count=count, offset=int(spec["offset"])
    )
    return arr.reshape(shape)


def attach_model(manifest: dict) -> Tuple[GameModel, ModelAttachment]:
    """Map a published model: verify the manifest self-digest, attach
    and checksum every segment, and only then reconstruct the
    :class:`GameModel` over zero-copy views.  Any failure raises
    :class:`ModelMapError` (and bumps ``model_map_unverified_total``)
    with nothing mapped."""
    if not isinstance(manifest, dict) or manifest.get("format") != (
        MANIFEST_FORMAT
    ):
        _unverified(
            "not a shared-memory model manifest (expected format "
            f"{MANIFEST_FORMAT!r}, got "
            f"{manifest.get('format') if isinstance(manifest, dict) else type(manifest).__name__!r})"
        )
    for field in ("version", "task", "coordinates", "segments",
                  "manifest_sha256", "publisher_pid"):
        if field not in manifest:
            _unverified(f"torn manifest: missing field {field!r}")
    expected = _manifest_digest(manifest)
    if manifest["manifest_sha256"] != expected:
        _unverified(
            "torn manifest: self-digest mismatch (body hashes to "
            f"{expected[:12]}…, manifest claims "
            f"{str(manifest['manifest_sha256'])[:12]}…) — refusing to "
            "map from an inconsistent manifest"
        )
    publisher_pid = int(manifest["publisher_pid"])
    attached: Dict[str, shared_memory.SharedMemory] = {}
    try:
        for name, spec in manifest["segments"].items():
            attached[name] = _attach_segment(name, spec, publisher_pid)
        models = {}
        for coord in manifest["coordinates"]:
            shm = attached[coord["segment"]]
            arrays = coord["arrays"]
            if coord["kind"] == "fixed":
                models[coord["name"]] = FixedEffectModel(
                    model=GeneralizedLinearModel(
                        coefficients=Coefficients(
                            means=_view(shm, arrays["means"])
                        ),
                        task=coord["task"],
                    ),
                    feature_shard=coord["feature_shard"],
                )
            elif coord["kind"] == "random":
                table = SharedEntityTable(
                    blob=_view(shm, arrays["ids_blob"]),
                    id_offsets=_view(shm, arrays["id_offsets"]),
                    row_offsets=_view(shm, arrays["row_offsets"]),
                    cols=_view(shm, arrays["cols"]),
                    vals=_view(shm, arrays["vals"]),
                )
                models[coord["name"]] = RandomEffectModel(
                    coefficients=table,
                    feature_shard=coord["feature_shard"],
                    entity_key=coord["entity_key"],
                    task=coord["task"],
                    n_features=int(coord["n_features"]),
                )
            else:
                _unverified(
                    f"torn manifest: unknown coordinate kind "
                    f"{coord['kind']!r}"
                )
    except ModelMapError:
        for shm in attached.values():
            try:
                shm.close()
            except BufferError:
                pass
        raise
    except Exception as exc:
        for shm in attached.values():
            try:
                shm.close()
            except BufferError:
                pass
        _unverified(f"shared model attach failed: {exc}")
    model = GameModel(models=models, task=manifest["task"])
    return model, ModelAttachment(manifest=manifest, segments=attached)
