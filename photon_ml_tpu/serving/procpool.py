"""Process-backed replica pool: crash-isolated workers behind the supervisor.

The GIL-escape half of the serving HA story (docs/serving.md
"Process-level workers"): each replica is an OS process
(serving/worker.py) with its own fault domain — a native crash, an OOM
kill, or a SIGKILL costs one worker, never the serving parent.  The
pieces:

- :class:`WorkerPool` — publishes the model into shared memory ONCE
  (serving/shm_model.py; N workers map ~1x the bytes, reported by
  ``serving_shared_segment_bytes``), tracks model generations for the
  swap/rollback window (the last TWO stay linked so a worker restarted
  mid-swap can still attach), parses requests parent-side via
  :class:`~photon_ml_tpu.serving.runtime.RequestParser`, and merges
  every worker's heartbeat metrics into the parent registry so
  /metrics, /stats, and the flight recorder keep a pool-wide view.
- :class:`ProcessReplica` — the parent-side stub satisfying the
  supervisor's route/probe/restart interface (``submit`` / ``stop`` /
  ``runtime`` / ``queue_depth`` / ``stats``, plus ``kill`` for scripted
  crashes): spawns its worker (spawn context — fork is unsafe once jax
  threads exist), frames requests over the socketpair, resolves futures
  off a reader thread, and on worker death fails every in-flight row
  with the watchdog's TRANSIENT vocabulary — which is exactly what
  makes the supervisor resubmit them to a peer, so a SIGKILL under load
  costs zero failed requests.

The chaos seam ``serving.worker`` fires at routing time and — unlike
the in-process ``serving.replica`` seam — actually SIGKILLs the routed
worker before raising, so a scripted fault exercises the real
death-mid-batch path: EOF on the pipe, transient failure of in-flight
rows, supervisor mark-down, decorrelated-jitter respawn.
"""

from __future__ import annotations

import dataclasses
import multiprocessing
import queue
import socket
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.serving import shm_model
from photon_ml_tpu.serving import worker as worker_mod
from photon_ml_tpu.serving.batcher import (
    BatcherConfig,
    DeadlineExceededError,
    RejectedError,
)
from photon_ml_tpu.serving.protocol import FrameConn
from photon_ml_tpu.serving.runtime import RequestParser, RuntimeConfig

__all__ = ["WorkerPool", "ProcessReplica"]


@dataclasses.dataclass
class _Generation:
    """One published model generation: its shared-memory manifest plus
    the parent-side parser state needed to serve it.  The host-side
    model object is retained so the delta-apply path (serving/swap.py
    ``swap_delta``) can patch the CURRENT generation parent-side and
    publish the result as the next one — without it, a delta would have
    to re-load the base from disk, defeating the point."""

    manifest: dict
    parser: RequestParser
    version: int
    path: Optional[str]
    model: object = None
    index_maps: Optional[dict] = None
    #: the runtime_config a tenant swap carried, so a respawned worker
    #: replays the route with the same knobs (None = pool default).
    runtime_config: Optional[RuntimeConfig] = None


class _WorkerRuntimeView:
    """What ``replica.batcher.runtime`` reads as in pool mode: the
    heartbeat-fed identity/health attributes the supervisor, service,
    and swapper consult via getattr — never a scorable runtime (scoring
    lives in the worker process)."""

    def __init__(self, pool: "WorkerPool"):
        self._pool = pool
        self.model_version = pool.version
        self.model_path = pool.model_path
        self.degraded = False
        self.ready = False
        self.pid: Optional[int] = None

    @property
    def config(self) -> RuntimeConfig:
        return self._pool.runtime_config

    def parse_request(self, obj: dict):
        return self._pool.parser.parse(obj)


class _PoolRuntimeView:
    """Pool-level stand-in for ``ScoringService.current_runtime``:
    version identity from the pool's current generation, parsing via
    the shared parser.  The service's isinstance(ScoringRuntime) guards
    skip runtime-only extras for it by design."""

    def __init__(self, pool: "WorkerPool"):
        self._pool = pool

    @property
    def model_version(self) -> int:
        return self._pool.version

    @property
    def model_path(self) -> Optional[str]:
        return self._pool.model_path

    @property
    def config(self) -> RuntimeConfig:
        return self._pool.runtime_config

    ready = True
    degraded = False

    def parse_request(self, obj: dict):
        return self._pool.parser.parse(obj)

    def probe_row(self):
        return self._pool.parser.probe_row()

    def stats(self) -> dict:
        return self._pool.stats()


class ProcessReplica:
    """Parent-side handle on one worker process, duck-typed to the
    MicroBatcher surface the supervisor routes/probes/stops."""

    def __init__(
        self,
        pool: "WorkerPool",
        rid: int,
        batcher_config: Optional[BatcherConfig] = None,
        start_timeout_s: float = 120.0,
    ):
        self.pool = pool
        self.rid = rid
        self.config = batcher_config or BatcherConfig()
        self.runtime = _WorkerRuntimeView(pool)
        self._lock = sanitizers.tracked(
            threading.Lock(), "serving.procpool"
        )
        self._inflight: Dict[int, Future] = {}
        self._next_id = 0
        # Parent-side backstop only — real admission control runs in the
        # worker's batcher; this just bounds parent memory if a worker
        # wedges with the socket open.
        self._max_inflight = 4 * self.config.max_queue
        self._control: "queue.Queue" = queue.Queue()
        self._ready_evt = threading.Event()
        self._bye = threading.Event()
        self._fatal: Optional[str] = None
        self._stopped = False
        self._hb: dict = {}

        parent_sock, child_sock = socket.socketpair()
        self._proc = pool._ctx.Process(
            target=worker_mod.worker_main,
            args=(
                child_sock, pool.manifest, rid,
                pool.runtime_config, self.config,
                pool.heartbeat_interval_s,
            ),
            name=f"photon-serving-worker-{rid}",
            daemon=True,
        )
        self._proc.start()
        child_sock.close()
        self._conn = FrameConn(parent_sock)
        self._reader = threading.Thread(
            target=self._read_loop,
            name=f"procpool-reader-{rid}",
            daemon=True,
        )
        self._reader.start()
        if not self._ready_evt.wait(start_timeout_s):
            self.stop(timeout=1.0)
            raise RuntimeError(
                f"UNAVAILABLE: worker {rid} did not become ready within "
                f"{start_timeout_s}s"
            )
        if self._fatal is not None or not self.runtime.ready:
            # A fatal frame, or EOF before the ready frame (the worker
            # died during spawn/import) — either way it never came up.
            error = self._fatal or "worker exited before becoming ready"
            self.stop(timeout=1.0)
            raise RuntimeError(f"worker {rid} failed to start: {error}")
        pool._register(self)
        # Replay committed tenant routes: a worker respawned after a
        # tenant swap must serve the same tenant → version map as its
        # peers, or a restart would silently undo a tenant's isolation.
        # A replay failure fails the spawn — the supervisor's restart
        # path reschedules with backoff rather than admitting a worker
        # with a stale route table.
        try:
            for tenant, generation in pool.tenant_generations().items():
                self.swap_prepare(
                    generation.manifest, generation.runtime_config
                )
                self.swap_commit(generation.version, tenant=tenant)
        except Exception as exc:
            self.stop(timeout=1.0)
            raise RuntimeError(
                f"worker {rid} failed to replay tenant routes: {exc}"
            ) from exc

    # -- reader thread -----------------------------------------------------
    def _read_loop(self) -> None:
        while True:
            try:
                message = self._conn.recv()
            except Exception:  # noqa: BLE001 — desync == worker gone
                message = None
            if message is None:
                break
            kind = message.get("kind")
            if kind == "result":
                self._resolve(message)
            elif kind == "heartbeat":
                self._on_heartbeat(message)
            elif kind == "ready":
                self.runtime.ready = True
                self.runtime.pid = message.get("pid")
                self.runtime.model_version = message.get(
                    "model_version", self.runtime.model_version
                )
                self._ready_evt.set()
            elif kind == "fatal":
                self._fatal = message.get("error")
                self._ready_evt.set()
            elif kind == "bye":
                self._bye.set()
            elif kind in ("swap_ready", "swap_failed", "swap_done"):
                self._control.put(message)
        # EOF: the worker is gone.  Every in-flight row fails with the
        # transient vocabulary — the supervisor's _on_done resubmits
        # each to a peer, which is the zero-failed-requests contract.
        self._fail_inflight(
            f"UNAVAILABLE: worker process {self.rid} died mid-request; "
            "resubmitting to a peer"
        )
        self.runtime.ready = False
        self._control.put({"kind": "eof"})
        self._ready_evt.set()

    def _resolve(self, message: dict) -> None:
        with self._lock:
            future = self._inflight.pop(message.get("id"), None)
        if future is None or not future.set_running_or_notify_cancel():
            return
        if message.get("ok"):
            future.set_result(message.get("value"))
            return
        error = message.get("error") or "worker error"
        error_kind = message.get("error_kind")
        if error_kind == "rejected":
            future.set_exception(RejectedError(error))
        elif error_kind == "deadline":
            future.set_exception(DeadlineExceededError(error))
        else:
            future.set_exception(RuntimeError(error))

    def _on_heartbeat(self, message: dict) -> None:
        self._hb = message
        self.runtime.model_version = message.get(
            "model_version", self.runtime.model_version
        )
        self.runtime.degraded = bool(message.get("degraded", False))
        self.runtime.ready = bool(message.get("ready", True))
        self.pool._absorb(self.rid, message)

    def _fail_inflight(self, reason: str) -> None:
        with self._lock:
            pending = list(self._inflight.values())
            self._inflight.clear()
        for future in pending:
            if future.set_running_or_notify_cancel():
                future.set_exception(RuntimeError(reason))

    # -- MicroBatcher surface ----------------------------------------------
    def submit(
        self,
        row,
        timeout_ms: Optional[float] = None,
        bypass_admission: bool = False,
    ) -> Future:
        # The scripted-crash seam: unlike the in-process serving.replica
        # seam, a fault here SIGKILLs the routed worker for real before
        # raising, so the whole death-mid-batch path (pipe EOF →
        # transient in-flight failure → resubmission → respawn) runs.
        try:
            chaos_mod.maybe_fail("serving.worker", worker=self.rid)
        except Exception:
            self.kill("chaos: serving.worker fault")
            raise
        with self._lock:
            if self._stopped or not self._proc.is_alive():
                raise RuntimeError(
                    f"UNAVAILABLE: worker process {self.rid} is not "
                    "running; retry with backoff"
                )
            if (
                len(self._inflight) >= self._max_inflight
                and not bypass_admission
            ):
                raise RejectedError(
                    f"UNAVAILABLE: worker {self.rid} in-flight window "
                    f"full ({self._max_inflight} pending); retry with "
                    "backoff"
                )
            request_id = self._next_id
            self._next_id += 1
            future: Future = Future()
            self._inflight[request_id] = future
        # Cross-process trace propagation: the submitting span's global
        # context rides the score frame (wire.py meta:trace column) so
        # the worker's serving.batch span parents to it and the request
        # stitches into ONE trace across the process boundary.
        pctx = telemetry_mod.current().propagation_context()
        message = {
            "kind": "score",
            "id": request_id,
            "row": row,
            # The tenant id rides the frame explicitly (not only
            # inside the pickled row) so the worker can stamp rows
            # built by older parsers and the wire stays greppable.
            "tenant": getattr(row, "tenant", None),
            "timeout_ms": timeout_ms,
            "bypass": bypass_admission,
        }
        if pctx is not None:
            message["trace"] = pctx.header_value()
        if getattr(row, "want_stages", False):
            # Stage-annotation opt-in must survive the wire fast path
            # (which re-builds the row from columns); the flag rides the
            # frame and the worker re-stamps the row.
            message["stages"] = True
        try:
            self._conn.send(message)
        except Exception as exc:  # noqa: BLE001 — connection is gone
            with self._lock:
                self._inflight.pop(request_id, None)
            raise RuntimeError(
                f"UNAVAILABLE: lost connection to worker {self.rid}: "
                f"{exc}"
            ) from exc
        return future

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._inflight)

    def stats(self) -> dict:
        """Request-response stats from the live worker, falling back to
        the last heartbeat when it is slow or gone."""
        try:
            with self._lock:
                request_id = self._next_id
                self._next_id += 1
                future = Future()
                self._inflight[request_id] = future
            self._conn.send({"kind": "stats", "id": request_id})
            stats = future.result(timeout=2.0)
        except Exception:  # noqa: BLE001 — fall back to heartbeat view
            stats = {
                "source": "heartbeat",
                "queue_depth": self._hb.get("queue_depth", 0),
                "model_version": self.runtime.model_version,
            }
        stats["replica"] = self.rid
        stats["inflight"] = self.queue_depth
        stats["alive"] = self._proc.is_alive()
        return stats

    def set_tenant_quota(
        self,
        tenant: str,
        rate_rps,
        burst=None,
        timeout: float = 5.0,
    ) -> None:
        """Apply a fleet quota lease to the worker's batcher (the
        ``set_quota`` frame; admission runs worker-side in process
        mode).  Raises on an unknown tenant or a dead worker — the
        lease client treats either as one host's failed apply."""
        with self._lock:
            request_id = self._next_id
            self._next_id += 1
            future = Future()
            self._inflight[request_id] = future
        try:
            self._conn.send({
                "kind": "set_quota", "id": request_id,
                "tenant": tenant, "rate_rps": rate_rps, "burst": burst,
            })
        except Exception as exc:  # noqa: BLE001 — worker is gone
            with self._lock:
                self._inflight.pop(request_id, None)
            raise RuntimeError(
                f"UNAVAILABLE: lost connection to worker {self.rid}: "
                f"{exc}"
            ) from exc
        future.result(timeout=timeout)

    def kill(self, reason: str = "scripted kill") -> None:
        """SIGKILL the worker — no drain, no goodbye: the real crash.
        The reader thread's EOF handling fails in-flight rows
        transiently, and the supervisor's mark-down → backoff → respawn
        path takes it from there."""
        telemetry_mod.current().event(
            "serving.worker_killed", worker=self.rid, reason=reason
        )
        if self._proc.is_alive():
            self._proc.kill()

    def stop(self, timeout: float = 5.0) -> None:
        """Graceful drain: ask the worker to stop, then escalate.
        Idempotent — the supervision thread calls this every tick while
        the replica is down."""
        with self._lock:
            first = not self._stopped
            self._stopped = True
        if first:
            try:
                self._conn.send({"kind": "shutdown"})
            except Exception:  # noqa: BLE001 — already gone
                pass
        try:
            self._bye.wait(timeout)
            self._proc.join(timeout=timeout)
        finally:
            if self._proc.is_alive():
                self._proc.terminate()
                self._proc.join(timeout=2.0)
            if self._proc.is_alive():
                self._proc.kill()
                self._proc.join(timeout=2.0)
            self._conn.close()
            self._reader.join(timeout=2.0)
            self._fail_inflight(
                "UNAVAILABLE: batcher stopped before dispatch; retry "
                "with backoff"
            )
            self.pool._unregister(self)

    # -- swap protocol (serving/swap.py remote branch) ---------------------
    def _await_control(
        self, kinds: tuple, timeout: float, what: str
    ) -> dict:
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"{what} on worker {self.rid} timed out after "
                    f"{timeout}s"
                )
            try:
                message = self._control.get(timeout=remaining)
            except queue.Empty:
                continue
            if message.get("kind") == "eof":
                # Leave a marker for any later waiter before raising.
                self._control.put(message)
                raise RuntimeError(
                    f"UNAVAILABLE: worker {self.rid} died during {what}"
                )
            if message.get("kind") in kinds:
                return message

    def swap_prepare(
        self, manifest: dict, runtime_config=None,
        carry_hot: bool = False, timeout: float = 120.0,
    ) -> None:
        """Stage a published generation in the worker: attach + build +
        warm + probe off the request path; raises on any failure.
        ``carry_hot`` (the delta-apply path) asks the worker to clone
        its serving runtime's compiled kernels and hot sets around the
        attached model instead of rebuilding cold."""
        self._conn.send({
            "kind": "swap_prepare",
            "manifest": manifest,
            "runtime_config": runtime_config,
            "carry_hot": carry_hot,
        })
        message = self._await_control(
            ("swap_ready", "swap_failed"), timeout,
            f"swap_prepare(v{manifest.get('version')})",
        )
        if message["kind"] == "swap_failed":
            raise RuntimeError(
                f"worker {self.rid} failed to prepare "
                f"v{manifest.get('version')}: {message.get('error')}"
            )

    def swap_commit(
        self, version: int, timeout: float = 30.0,
        tenant: Optional[str] = None,
    ) -> None:
        """Commit a prepared version — as the default serving runtime,
        or (with ``tenant``) as that one tenant's route, leaving the
        worker's default runtime untouched."""
        frame = {"kind": "swap_commit", "version": version}
        if tenant is not None:
            frame["tenant"] = tenant
        self._conn.send(frame)
        self._await_control(
            ("swap_done",), timeout, f"swap_commit(v{version})"
        )

    def swap_rollback(
        self, timeout: float = 30.0, tenant: Optional[str] = None
    ) -> bool:
        """Restore the worker's retained previous runtime (or, with
        ``tenant``, that tenant's retained previous route).  Returns
        False when the worker had nothing retained (it was restarted
        after the commit and attached the new generation directly) —
        the caller converges it by killing it onto the restored
        generation."""
        frame: dict = {"kind": "swap_rollback"}
        if tenant is not None:
            frame["tenant"] = tenant
        self._conn.send(frame)
        message = self._await_control(
            ("swap_done",), timeout, "swap_rollback"
        )
        return bool(message.get("rolled_back", True))

    def swap_abort(self, version: int) -> None:
        try:
            self._conn.send({"kind": "swap_abort", "version": version})
        except Exception:  # noqa: BLE001 — worker gone; nothing staged
            pass


class WorkerPool:
    """Shared model state + spawn context for process replicas.

    Construct it with the loaded model, hand it to
    :class:`~photon_ml_tpu.serving.supervisor.ReplicaSupervisor` via
    ``pool=``, and the supervisor builds/restarts
    :class:`ProcessReplica` instances through :meth:`new_replica`
    instead of in-process batchers.  ``close()`` (called by the
    supervisor's stop) unlinks every published generation.
    """

    def __init__(
        self,
        model,
        index_maps: Optional[dict] = None,
        runtime_config: Optional[RuntimeConfig] = None,
        model_path: Optional[str] = None,
        version: int = 1,
        heartbeat_interval_s: float = 0.25,
        start_timeout_s: float = 120.0,
    ):
        # Spawn, never fork: by the time a pool exists the parent has
        # imported jax and holds live threads; forking them is undefined.
        self._ctx = multiprocessing.get_context("spawn")
        self.runtime_config = runtime_config or RuntimeConfig()
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.start_timeout_s = float(start_timeout_s)
        self._lock = sanitizers.tracked(
            threading.Lock(), "serving.procpool"
        )
        self._generations: List[_Generation] = [
            self.publish(model, index_maps, version=version,
                         path=model_path)
        ]
        # Tenant route registry: tenant → its committed generation, plus
        # one retained previous generation per tenant (the rollback
        # window).  Tenant generations live ONLY here — never in
        # ``_generations`` — so a tenant swap can never evict the
        # default route's rollback window and vice versa.
        self._tenant_generations: Dict[str, _Generation] = {}
        self._tenant_previous: Dict[str, Optional[_Generation]] = {}
        self._replicas: Dict[int, ProcessReplica] = {}
        self._hb_prev: Dict[int, dict] = {}
        self._view = _PoolRuntimeView(self)
        self._closed = False

    # -- current generation ------------------------------------------------
    @property
    def _current(self) -> _Generation:
        with self._lock:
            return self._generations[-1]

    @property
    def manifest(self) -> dict:
        return self._current.manifest

    @property
    def parser(self) -> RequestParser:
        return self._current.parser

    @property
    def version(self) -> int:
        return self._current.version

    @property
    def model_path(self) -> Optional[str]:
        return self._current.path

    # -- generation lifecycle (the swap machinery drives these) ------------
    def publish(
        self,
        model,
        index_maps: Optional[dict] = None,
        version: int = 1,
        path: Optional[str] = None,
    ) -> _Generation:
        """Pack a model into shared memory; the generation is STAGED
        (not current) until :meth:`commit_generation`."""
        manifest = shm_model.publish_model(model, version=version, path=path)
        parser = RequestParser.for_model(model, index_maps)
        return _Generation(
            manifest=manifest, parser=parser, version=version, path=path,
            model=model, index_maps=index_maps,
        )

    def current_model(self) -> tuple:
        """The host-side ``(model, index_maps)`` of the CURRENT
        generation — the base the delta-apply path patches."""
        current = self._current
        return current.model, current.index_maps

    def commit_generation(self, generation: _Generation) -> None:
        """Make a staged generation current.  Keeps the last TWO
        generations linked — the rollback window, and what a worker
        respawned mid-swap attaches — and unlinks anything older."""
        retired = []
        with self._lock:
            self._generations.append(generation)
            while len(self._generations) > 2:
                retired.append(self._generations.pop(0))
        for old in retired:
            shm_model.unpublish_model(old.manifest)

    def retire_generation(self, generation: _Generation) -> None:
        """Unlink a STAGED generation after a failed swap."""
        shm_model.unpublish_model(generation.manifest)

    def rollback_generation(self) -> _Generation:
        """Drop the current generation and restore the previous one
        (the swapper's one-step rollback)."""
        with self._lock:
            if len(self._generations) < 2:
                raise RuntimeError(
                    "no previous model generation to roll back to"
                )
            dropped = self._generations.pop()
        shm_model.unpublish_model(dropped.manifest)
        return self._current

    # -- tenant generations (serving/swap.py tenant-scoped swaps) ----------
    def _referenced_locked(self, generation: _Generation) -> bool:
        """Whether any registry slot still points at ``generation``
        (identity, not equality — generations wrap live model arrays).
        Call under ``self._lock``."""
        for g in self._generations:
            if g is generation:
                return True
        for g in self._tenant_generations.values():
            if g is generation:
                return True
        for g in self._tenant_previous.values():
            if g is generation:
                return True
        return False

    def tenant_generations(self) -> Dict[str, _Generation]:
        """Snapshot of committed tenant routes — what a respawned
        worker replays before taking traffic."""
        with self._lock:
            return dict(self._tenant_generations)

    def commit_tenant_generation(
        self, tenant: str, generation: _Generation
    ) -> None:
        """Make a staged generation the tenant's committed route.  The
        displaced route (if any) moves into the tenant's one-slot
        rollback window; whatever that evicts is unlinked unless some
        other slot still references it."""
        with self._lock:
            evicted = self._tenant_previous.get(tenant)
            self._tenant_previous[tenant] = (
                self._tenant_generations.get(tenant)
            )
            self._tenant_generations[tenant] = generation
            unlink = (
                evicted is not None
                and not self._referenced_locked(evicted)
            )
        if unlink:
            shm_model.unpublish_model(evicted.manifest)

    def rollback_tenant_generation(self, tenant: str) -> None:
        """Drop the tenant's committed generation and restore the one
        its last swap displaced (or no route at all — back to the
        default generation)."""
        with self._lock:
            dropped = self._tenant_generations.pop(tenant, None)
            previous = self._tenant_previous.pop(tenant, None)
            if previous is not None:
                self._tenant_generations[tenant] = previous
            unlink = (
                dropped is not None
                and not self._referenced_locked(dropped)
            )
        if unlink:
            shm_model.unpublish_model(dropped.manifest)

    # -- replicas ----------------------------------------------------------
    def new_replica(
        self,
        rid: int,
        batcher_config: Optional[BatcherConfig] = None,
        policy=None,  # accepted for interface parity; admission runs worker-side
    ) -> ProcessReplica:
        if self._closed:
            raise RuntimeError("worker pool is closed")
        return ProcessReplica(
            self, rid, batcher_config,
            start_timeout_s=self.start_timeout_s,
        )

    def _register(self, replica: ProcessReplica) -> None:
        with self._lock:
            if not self._closed:
                self._replicas[replica.rid] = replica
                return
        # The pool closed while this replica was spawning (a supervisor
        # restart racing stop()): close() snapshotted the replica map
        # before this one joined it, so reap it here — otherwise the
        # worker process outlives the pool and trips the strict
        # process-leak sentinels.  Failing the spawn sends the restart
        # path to its reschedule branch, which the stopping supervisor
        # never services again.
        replica.stop(timeout=1.0)
        raise RuntimeError("worker pool is closed")

    def _unregister(self, replica: ProcessReplica) -> None:
        with self._lock:
            if self._replicas.get(replica.rid) is replica:
                del self._replicas[replica.rid]
                self._hb_prev.pop(replica.rid, None)

    def runtime_view(self) -> _PoolRuntimeView:
        return self._view

    # -- telemetry merge ---------------------------------------------------
    def _absorb(self, rid: int, heartbeat: dict) -> None:
        """Fold one worker's cumulative metrics snapshot into the parent
        registry as a delta vs the last snapshot absorbed from that
        worker (telemetry/core.py transport discipline)."""
        metrics = heartbeat.get("metrics")
        if not metrics:
            return
        try:
            registry = telemetry_mod.current().metrics
            with self._lock:
                previous = self._hb_prev.get(rid)
                self._hb_prev[rid] = metrics
            registry.absorb_delta(metrics, previous)
        except Exception:  # noqa: BLE001 — telemetry must not kill reads
            pass

    # -- observability / shutdown ------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            replicas = sorted(self._replicas)
            tenant_versions = {
                tenant: generation.version
                for tenant, generation in self._tenant_generations.items()
            }
        return {
            "source": "pool",
            "workers": replicas,
            "model_version": self.version,
            "model_path": self.model_path,
            "generations": len(self._generations),
            "tenant_versions": tenant_versions,
            "live_segments": shm_model.live_segments(),
        }

    def close(self, timeout: float = 5.0) -> None:
        """Stop every worker, then unlink every generation.  Idempotent;
        after this the strict sentinels must see zero leaked processes
        and zero live segments."""
        with self._lock:
            if self._closed:
                return
            # Under the same lock as _register: every replica either
            # made this snapshot (stopped below) or will observe
            # _closed at registration and reap itself.
            self._closed = True
            replicas = list(self._replicas.values())
        for replica in replicas:
            replica.stop(timeout=timeout)
        with self._lock:
            generations = list(self._generations)
            for g in self._tenant_generations.values():
                generations.append(g)
            for g in self._tenant_previous.values():
                if g is not None:
                    generations.append(g)
            self._generations = self._generations[-1:]
            self._tenant_generations = {}
            self._tenant_previous = {}
        seen: set = set()
        for generation in generations:
            if id(generation) in seen:
                continue
            seen.add(id(generation))
            shm_model.unpublish_model(generation.manifest)
