"""Fleet tier: one router over N serving HOSTS + fleet-wide quota leases.

ROADMAP item 3's last open layer.  One ``ScoringService`` — even with a
``ReplicaSupervisor`` and process workers under it — is still ONE host:
one kernel, one NIC, one power feed.  This module is the node tier of
Snap ML's hierarchical split (PAPERS.md): whole hosts behind one front
door, built failure-first.

- :class:`FleetRouter` — routes scoring requests across N host
  endpoints (each a full ``ScoringService`` in thread or process mode,
  reached over the existing HTTP JSON protocol, serving/service.py).
  The supervisor's replica discipline, one tier up: requests round-robin
  over HEALTHY hosts; a transient host failure (connection refused,
  reset, 5xx, a watchdog-transient error body) marks the host DOWN and
  RESUBMITS the request to a peer — the client's future only fails when
  every host has been tried, so a host kill under load costs zero
  failed requests.  Down hosts are re-probed behind decorrelated-jitter
  backoff (``utils/watchdog.RetryPolicy``) and rejoin on sustained
  health, which also resets the backoff walk.  ``drain(hid)`` removes a
  host gracefully: no new routing, in-flight requests complete, then
  the host leaves the rotation.
- :class:`QuotaCoordinator` — turns the per-batcher ``TokenBucket``\\ s
  (serving/tenancy.py) into FLEET-accurate enforcement.  Each tenant
  has one fleet budget; hosts hold short-lived rate LEASES carved from
  it.  On every renewal the coordinator rebalances lease shares by
  observed per-host demand (with a min-share floor so a quiet host can
  still admit a sudden burst) and reclaims leases whose hosts stopped
  renewing (host death).  Outstanding grants never sum past the
  budget, so fleet-wide admission is bounded by construction.
- :class:`LeaseClient` — the host-side agent: measures this host's
  per-tenant demand (``ScoringService.demand_snapshot`` deltas), renews
  through the ``quota.lease`` chaos seam, and applies granted rates to
  the host's buckets via ``ScoringService.set_tenant_quota`` (thread
  mode mutates batcher buckets; process mode rides a ``set_quota``
  worker frame).  **The partition-tolerance contract:** a host that
  cannot reach the coordinator keeps enforcing its LAST lease — never
  unlimited, never zero — so a partition bounds fleet over-admission
  to one lease window (the stale host can only admit what it was last
  granted, and the coordinator stops counting that grant after
  ``lease_ttl_s``).
- :class:`LocalHost` — one in-process "host": a full ScoringService
  behind its own HTTP listener on an ephemeral port, with scripted
  ``kill()`` (listener torn down abruptly — new connections refuse,
  exactly what a crashed host looks like from the router) and
  ``restart()`` (rebind the same port).  The substrate for the
  ``host_kill`` / ``quota_partition`` scenarios, the fleet selfcheck,
  and bench gates; a production host runs the same service standalone.

Chaos seams: ``serving.host`` fires at routing time (a fault is a host
dying as it picks up the request — mark down + resubmit, zero failed
requests); ``quota.lease`` fires in the lease renewal (a fault is the
coordinator partition — degrade to the last lease).  Metric family:
``serving_fleet_*`` (docs/telemetry.md).  See docs/serving.md "Fleet"
and ops/README.md for the host-down / coordinator-unreachable runbooks.
"""

from __future__ import annotations

import dataclasses
import json
import queue
import random
import threading
import time
import urllib.error
import urllib.request
from concurrent.futures import Future
from typing import Callable, Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.serving.batcher import (
    DeadlineExceededError,
    RejectedError,
)
from photon_ml_tpu.serving import wire as wire_mod
from photon_ml_tpu.utils.watchdog import RetryPolicy


# ---------------------------------------------------------------------------
# HTTP plumbing (stdlib only; one fresh connection per request keeps the
# failure model simple — a dead host is ECONNREFUSED, not a stale pool)
# ---------------------------------------------------------------------------

def _http_json(
    method: str, url: str, payload: Optional[dict] = None,
    timeout_s: float = 30.0,
) -> tuple[int, dict]:
    """One JSON round-trip; returns ``(status, body)``.  Non-2xx statuses
    return normally (the body carries the verdict); only transport-level
    failures (refused, reset, timeout) raise."""
    data = None if payload is None else json.dumps(payload).encode()
    return _http_post_raw(
        url, data, "application/json", timeout_s, method=method
    )


def _http_post_raw(
    url: str, body: Optional[bytes], content_type: str,
    timeout_s: float = 30.0, method: str = "POST",
    headers: Optional[dict] = None,
) -> tuple[int, dict]:
    """One round-trip with a PRE-ENCODED body; returns ``(status,
    body_dict)``.  A binary response frame decodes into the same
    ``{"results": [...]}`` shape the JSON path returns (plus a
    top-level ``"error"`` mirror of the first failed row, so the
    status-code verdict logic reads both formats identically).
    ``headers`` adds extra request headers (the trace-context header
    rides here) without touching the content-type negotiation."""
    hdrs = {"Content-Type": content_type}
    if headers:
        hdrs.update(headers)
    req = urllib.request.Request(
        url, data=body, method=method, headers=hdrs,
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return resp.status, _parse_response(
                resp.headers.get("Content-Type"), resp.read()
            )
    except urllib.error.HTTPError as exc:
        return exc.code, _parse_response(
            exc.headers.get("Content-Type") if exc.headers else None,
            exc.read(),
        )


def _parse_response(content_type: Optional[str], raw: bytes) -> dict:
    ctype = (content_type or "").split(";", 1)[0].strip().lower()
    if ctype == wire_mod.CONTENT_TYPE:
        try:
            results = wire_mod.decode_response(raw)
        except wire_mod.WireFormatError as exc:
            return {"error": f"bad response frame: {exc}"}
        out = {"results": results}
        if results and isinstance(results[0], dict) \
                and "error" in results[0]:
            out["error"] = results[0]["error"]
        return out
    try:
        return json.loads(raw or b"{}")
    except json.JSONDecodeError:
        return {"error": raw.decode(errors="replace")}


_ERROR_BUILDERS = {
    "rejected": RejectedError,
    "deadline": DeadlineExceededError,
    "bad_request": ValueError,
}

_STATUS_KIND = {429: "rejected", 504: "deadline", 400: "bad_request"}


# ---------------------------------------------------------------------------
# FleetRouter
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class _FleetHost:
    hid: int
    base_url: str
    state: str = "healthy"  # "healthy" | "down" | "draining" | "removed"
    inflight: int = 0
    probe_failures: int = 0
    reconnect_attempt: int = 0
    last_delay: Optional[float] = None
    next_reconnect_t: float = 0.0
    reconnects: int = 0
    down_reason: Optional[str] = None
    requests: int = 0


_STOP = object()


class FleetRouter:
    """Front-tier router over N host endpoints (HTTP base URLs).

    Mirrors enough of the ``ScoringService`` surface (``submit`` /
    ``score`` / ``score_many`` / ``healthz`` / ``readiness`` /
    ``stats``) that loadgen, scenarios, and callers compose with a
    fleet exactly as they do with one service.  ``submit`` takes the
    WIRE request (the JSON dict a client would POST) — parsing happens
    host-side, where the model lives.
    """

    def __init__(
        self,
        endpoints: list,
        policy: Optional[RetryPolicy] = None,
        reconnect_policy: Optional[RetryPolicy] = None,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 5.0,
        probe_failure_threshold: int = 2,
        request_timeout_s: float = 30.0,
        no_host_retry_s: float = 5.0,
        workers: int = 16,
        max_pending: int = 1024,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
        wire_format: str = "json",
    ):
        if not endpoints:
            raise ValueError("FleetRouter needs at least one endpoint")
        if wire_format not in ("json", "binary"):
            raise ValueError(
                f"wire_format must be 'json' or 'binary', got "
                f"{wire_format!r}"
            )
        #: request encoding toward the hosts: "binary" sends wire
        #: frames (serving/wire.py) and falls back to JSON per-request
        #: when a row is not frame-encodable (named sparse features).
        self.wire_format = wire_format
        self.policy = policy or RetryPolicy()
        self.reconnect_policy = reconnect_policy or RetryPolicy(
            backoff_seconds=0.05,
            max_backoff_seconds=2.0,
            jitter="decorrelated",
        )
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.probe_failure_threshold = probe_failure_threshold
        self.request_timeout_s = request_timeout_s
        #: how long a request with NO healthy host waits for reconnect
        #: probes to restore one before failing — a whole-fleet blip
        #: (every host mid-reconnect at once) delays requests instead
        #: of failing them, the same contract a single host's kill has.
        self.no_host_retry_s = no_host_retry_s
        self.max_pending = max_pending
        self._rng = rng or random.Random(0)
        self._clock = clock
        self.hosts = [
            _FleetHost(hid=i, base_url=str(url).rstrip("/"))
            for i, url in enumerate(endpoints)
        ]
        self._lock = sanitizers.tracked(threading.Lock(), "serving.fleet")
        self._rr = 0
        self._queue: "queue.Queue" = queue.Queue(maxsize=max_pending)
        self._workers = max(1, int(workers))
        self._threads: list[threading.Thread] = []
        self._probe_thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._started = False

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "FleetRouter":
        if self._started:
            return self
        self._stop_evt.clear()
        for i in range(self._workers):
            t = threading.Thread(
                target=self._work_loop, name=f"fleet-router-{i}",
                daemon=True,
            )
            t.start()
            self._threads.append(t)
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="fleet-probe", daemon=True
        )
        self._probe_thread.start()
        self._started = True
        tel = telemetry_mod.current()
        tel.gauge("serving_fleet_hosts_count").set(len(self.hosts))
        tel.gauge("serving_fleet_healthy_hosts_count").set(
            self.healthy_count
        )
        return self

    def stop(self, timeout: float = 5.0) -> None:
        if not self._started:
            return
        self._stop_evt.set()
        for _ in self._threads:
            self._queue.put(_STOP)
        for t in self._threads:
            t.join(timeout=timeout)
        self._threads = []
        thread = self._probe_thread
        self._probe_thread = None
        if thread is not None:
            thread.join(timeout=timeout)
        # Fail anything that raced past submit after the stop — no
        # worker will ever route it.  Transient vocabulary, like the
        # batcher's drain: the caller may retry against a new router.
        while True:
            try:
                item = self._queue.get_nowait()
            except queue.Empty:
                break
            if item is _STOP:
                continue
            fut = item[1]
            if fut.set_running_or_notify_cancel():
                fut.set_exception(RuntimeError(
                    "UNAVAILABLE: fleet router stopped before dispatch; "
                    "retry with backoff"
                ))
        self._started = False

    def __enter__(self) -> "FleetRouter":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    # -- submission (any thread) -------------------------------------------
    def submit(self, request: dict) -> Future:
        """Enqueue one wire request; returns a future resolving to the
        per-row result dict.  Raises RejectedError when the router's own
        pending queue is full (backpressure, not a host verdict)."""
        if not self._started:
            raise RuntimeError("fleet router is not started")
        # The router is the request's entry into the fleet: mint the
        # ROOT trace context here (head sampling decides once, every
        # downstream hop re-derives the verdict from the id) — unless
        # the caller is already inside a traced request, whose context
        # propagates instead.
        tel = telemetry_mod.current()
        ctx = tel.propagation_context()
        if ctx is None and tel.active:
            ctx = tel.new_trace()
        fut: Future = Future()
        try:
            self._queue.put_nowait(
                (request, fut, time.perf_counter(), ctx)
            )
        except queue.Full:
            telemetry_mod.current().counter(
                "serving_fleet_rejected_total"
            ).inc()
            raise RejectedError(
                f"UNAVAILABLE: fleet router pending queue full "
                f"({self.max_pending}); retry with backoff"
            ) from None
        telemetry_mod.current().counter(
            "serving_fleet_requests_total"
        ).inc()
        return fut

    def score(self, request: dict, timeout: Optional[float] = 30.0) -> dict:
        return self.submit(request).result(timeout=timeout)

    def score_many(
        self, requests: list, timeout: Optional[float] = 30.0
    ) -> list:
        slots: list = [None] * len(requests)
        futures = []
        for i, req in enumerate(requests):
            try:
                futures.append((i, self.submit(req)))
            except (RejectedError, ValueError) as exc:
                slots[i] = {"error": str(exc), "kind": "rejected"}
        for i, fut in futures:
            try:
                slots[i] = fut.result(timeout=timeout)
            except Exception as exc:  # noqa: BLE001 — per-row reporting
                slots[i] = {"error": str(exc), "kind": "error"}
        return slots

    # -- routing (worker threads) ------------------------------------------
    def _work_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                return
            try:
                self._route(item)
            except Exception as exc:  # noqa: BLE001 — never kill a worker
                fut = item[1]
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(exc)

    def _pick(self, tried: set) -> Optional[_FleetHost]:
        with self._lock:
            candidates = [
                h for h in self.hosts
                if h.state == "healthy" and h.hid not in tried
            ]
            if not candidates:
                return None
            self._rr += 1
            host = candidates[self._rr % len(candidates)]
            host.inflight += 1
            host.requests += 1
            return host

    def _release(self, host: _FleetHost) -> None:
        with self._lock:
            host.inflight -= 1

    def _encode_request(
        self, request: dict, trace: Optional[str] = None
    ) -> tuple[bytes, str]:
        """Encode one wire request body, ONCE per routed request — the
        peer-retry loop reuses these bytes on every resubmission, so a
        retry costs a socket, never a re-serialization.  ``trace`` rides
        the frame's v2 ``trace:ctx`` column on the binary path (the
        JSON path carries it as an HTTP header instead)."""
        if self.wire_format == "binary":
            try:
                return (
                    wire_mod.encode_request([request], trace=trace),
                    wire_mod.CONTENT_TYPE,
                )
            except ValueError:
                # Not frame-encodable (named sparse features) — the
                # JSON compatibility path carries it instead.
                pass
        return (
            json.dumps({"rows": [request]}).encode(),
            "application/json",
        )

    def _route(self, item) -> None:
        request, fut, t_submit, ctx = item
        tel = telemetry_mod.current()
        # The routing span is the trace's root span on this node: every
        # host-side hop parents to it via the propagated context (HTTP
        # header on the JSON path, wire v2 trace:ctx column on the
        # binary path), so one fleet request reads as ONE stitched tree
        # across router, host, and worker processes.
        with tel.adopt(ctx), tel.span("serving.fleet_route"):
            pctx = tel.propagation_context()
            trace_value = None if pctx is None else pctx.header_value()
            headers = (
                {telemetry_mod.TRACE_HEADER: trace_value}
                if trace_value is not None else None
            )
            body, content_type = self._encode_request(request, trace_value)
            self._route_one(
                fut, t_submit, body, content_type, headers, tel
            )

    def _route_one(
        self, fut, t_submit, body, content_type, headers, tel
    ) -> None:
        tried: set = set()
        last_reject: Optional[Exception] = None
        no_host_deadline: Optional[float] = None
        while True:
            host = self._pick(tried)
            if host is None:
                # An admission verdict (every host shed the row) is
                # final here: the caller must back off, peers spinning
                # would only re-offer over-quota work.
                if last_reject is None:
                    # Transport/outage verdicts are not: wait for the
                    # reconnect probes to restore a host (a killed host
                    # delays requests, never fails them — including the
                    # window where EVERY host is momentarily down).
                    now = self._clock()
                    if no_host_deadline is None:
                        no_host_deadline = now + self.no_host_retry_s
                    if now < no_host_deadline and not \
                            self._stop_evt.wait(0.02):
                        tried.clear()
                        continue
                exc = last_reject or RejectedError(
                    "UNAVAILABLE: no healthy host "
                    f"({self.healthy_count} healthy, {len(tried)} "
                    "tried); retry with backoff"
                )
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(exc)
                return
            try:
                # The scripted-crash seam: a fault here is the host
                # dying as it picks up the request (docs/robustness.md).
                chaos_mod.maybe_fail("serving.host", host=host.hid)
                status, obj = _http_post_raw(
                    host.base_url + "/score", body, content_type,
                    self.request_timeout_s, headers=headers,
                )
            except Exception as exc:  # noqa: BLE001 — transport failure
                self._release(host)
                self._mark_down(host, f"request failed: {exc}"[:200])
                tried.add(host.hid)
                tel.counter("serving_fleet_resubmitted_total").inc()
                continue
            self._release(host)
            verdict = self._verdict(status, obj)
            kind, payload = verdict
            if kind == "ok":
                if fut.set_running_or_notify_cancel():
                    fut.set_result(payload)
                tel.histogram(
                    "serving_fleet_request_latency_seconds"
                ).observe(time.perf_counter() - t_submit)
                return
            if kind == "rejected":
                # This host's admission control shed the row; a peer
                # below its watermarks (or with lease tokens left) may
                # still admit it — total admission stays budget-bounded
                # because every host draws from its own lease.
                tried.add(host.hid)
                last_reject = payload
                continue
            if kind == "transient":
                # The HOST's fault (5xx, transient error body): mark it
                # down and resubmit to a peer.
                self._mark_down(host, f"transient failure: {payload}")
                tried.add(host.hid)
                tel.counter("serving_fleet_resubmitted_total").inc()
                continue
            # The REQUEST's own verdict (expired deadline, bad input) —
            # another host would only repeat it.
            if fut.set_running_or_notify_cancel():
                fut.set_exception(payload)
            return

    def _verdict(self, status: int, obj: dict) -> tuple:
        """Map one HTTP response to a routing verdict:
        ``("ok", result)`` / ``("rejected", exc)`` / ``("final", exc)``
        / ``("transient", reason_str)``."""
        if status == 200:
            results = obj.get("results") or [{}]
            result = results[0] if results else {}
            if not isinstance(result, dict) or "error" not in result:
                return ("ok", result)
            kind = result.get("kind", "internal")
            message = str(result.get("error", ""))
            if kind in _ERROR_BUILDERS:
                exc = _ERROR_BUILDERS[kind](message)
                if kind == "rejected":
                    return ("rejected", exc)
                return ("final", exc)
            # "internal": classify the message — the transient
            # vocabulary (UNAVAILABLE, worker died, ...) is the host's
            # fault and resubmits; anything else is final.
            if self.policy.classify(RuntimeError(message)).transient:
                return ("transient", message[:200])
            return ("final", RuntimeError(message))
        kind = _STATUS_KIND.get(status)
        if kind is not None:
            message = str(obj.get("error") or obj)[:500]
            exc = _ERROR_BUILDERS[kind](message)
            if kind == "rejected":
                return ("rejected", exc)
            return ("final", exc)
        return ("transient", f"HTTP {status}: {obj.get('error', obj)}"[:200])

    # -- failure handling --------------------------------------------------
    @property
    def healthy_count(self) -> int:
        with self._lock:
            return sum(1 for h in self.hosts if h.state == "healthy")

    def _mark_down(self, host: _FleetHost, reason: str) -> None:
        """Exclude a host from routing and schedule reconnect probes
        with decorrelated-jitter backoff.  Never blocks."""
        with self._lock:
            if host.state != "healthy":
                return
            host.state = "down"
            host.down_reason = reason
            host.probe_failures = 0
            delay = self.reconnect_policy.backoff(
                host.reconnect_attempt, rng=self._rng,
                previous=host.last_delay,
            )
            host.reconnect_attempt += 1
            host.last_delay = delay
            host.next_reconnect_t = self._clock() + delay
        tel = telemetry_mod.current()
        tel.counter("serving_fleet_host_down_total").inc()
        tel.gauge("serving_fleet_healthy_hosts_count").set(
            self.healthy_count
        )
        tel.event(
            "serving.fleet_host_down",
            host=host.hid,
            url=host.base_url,
            reason=reason,
            reconnect_in_s=round(delay, 4),
        )

    # -- probing (supervision thread) --------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop_evt.wait(self.probe_interval_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — supervision must survive
                pass

    def _tick(self) -> None:
        now = self._clock()
        for host in list(self.hosts):
            if self._stop_evt.is_set():
                return
            if host.state == "down" and now >= host.next_reconnect_t:
                self._reconnect_probe(host, now)
            elif host.state == "healthy":
                self._probe(host)

    def _probe_ready(self, host: _FleetHost) -> bool:
        status, _ = _http_json(
            "GET", host.base_url + "/readyz",
            timeout_s=self.probe_timeout_s,
        )
        return status == 200

    def _probe(self, host: _FleetHost) -> None:
        try:
            ready = self._probe_ready(host)
            if not ready:
                raise RuntimeError("host reports not_ready")
        except Exception as exc:  # noqa: BLE001 — any failure counts
            host.probe_failures += 1
            telemetry_mod.current().counter(
                "serving_fleet_probe_failures_total"
            ).inc()
            if host.probe_failures >= self.probe_failure_threshold:
                self._mark_down(
                    host,
                    f"{host.probe_failures} consecutive probe failures "
                    f"(last: {exc})"[:200],
                )
            return
        host.probe_failures = 0
        # Sustained health resets the backoff walk — same contract as
        # the supervisor one tier down (a host answering probes again
        # is trusted again; flapping hosts re-escalate from base).
        host.reconnect_attempt = 0
        host.last_delay = None

    def _reconnect_probe(self, host: _FleetHost, now: float) -> None:
        try:
            if not self._probe_ready(host):
                raise RuntimeError("host reports not_ready")
        except Exception:  # noqa: BLE001 — still down; re-schedule
            with self._lock:
                delay = self.reconnect_policy.backoff(
                    host.reconnect_attempt, rng=self._rng,
                    previous=host.last_delay,
                )
                host.reconnect_attempt += 1
                host.last_delay = delay
                host.next_reconnect_t = self._clock() + delay
            return
        with self._lock:
            host.state = "healthy"
            host.probe_failures = 0
            host.down_reason = None
            host.reconnects += 1
        tel = telemetry_mod.current()
        tel.counter("serving_fleet_reconnects_total").inc()
        tel.gauge("serving_fleet_healthy_hosts_count").set(
            self.healthy_count
        )
        tel.event(
            "serving.fleet_host_reconnected",
            host=host.hid,
            reconnects=host.reconnects,
        )

    # -- draining / membership ---------------------------------------------
    def drain(self, hid: int, timeout_s: float = 10.0) -> bool:
        """Graceful host removal: stop routing NEW requests to ``hid``,
        wait for its in-flight requests to complete, then take it out of
        the rotation.  Returns True when the host drained inside the
        timeout (False leaves it 'draining': still unrouted, still
        counted in-flight — retry or escalate to kill)."""
        host = next((h for h in self.hosts if h.hid == hid), None)
        if host is None:
            raise ValueError(
                f"unknown host id {hid!r}; known: "
                f"{sorted(h.hid for h in self.hosts)}"
            )
        with self._lock:
            if host.state == "removed":
                return True
            host.state = "draining"
        tel = telemetry_mod.current()
        tel.gauge("serving_fleet_healthy_hosts_count").set(
            self.healthy_count
        )
        deadline = self._clock() + timeout_s
        while self._clock() < deadline:
            with self._lock:
                drained = host.inflight == 0
            if drained:
                with self._lock:
                    host.state = "removed"
                tel.counter("serving_fleet_drains_total").inc()
                tel.event("serving.fleet_host_drained", host=hid)
                return True
            time.sleep(0.005)
        return False

    def add_host(self, base_url: str) -> int:
        """Add a host to the rotation (it must already answer /readyz —
        probe verdicts take over from there)."""
        with self._lock:
            hid = max((h.hid for h in self.hosts), default=-1) + 1
            self.hosts.append(
                _FleetHost(hid=hid, base_url=str(base_url).rstrip("/"))
            )
        telemetry_mod.current().gauge("serving_fleet_hosts_count").set(
            len(self.hosts)
        )
        return hid

    def join(self, base_url: str) -> int:
        """Symmetric counterpart to :meth:`drain`: register a host into
        the LIVE rotation without a router restart.  Unlike
        :meth:`add_host` (which trusts the caller and routes
        immediately), a joined host enters as ``down`` with an
        immediate reconnect probe scheduled — it starts taking traffic
        only after it answers ``/readyz``, so joining a host that is
        still warming up never costs a request.  Re-joining a known URL
        (drained/removed or currently down) revives the SAME host id
        with fresh probe state.  Returns the host id."""
        url = str(base_url).rstrip("/")
        with self._lock:
            host = next(
                (h for h in self.hosts if h.base_url == url), None
            )
            if host is not None and host.state in ("healthy", "draining"):
                # Already in rotation: joining is idempotent.
                return host.hid
            if host is None:
                hid = max((h.hid for h in self.hosts), default=-1) + 1
                host = _FleetHost(hid=hid, base_url=url)
                self.hosts.append(host)
            host.state = "down"
            host.down_reason = "joining (awaiting first ready probe)"
            host.probe_failures = 0
            host.reconnect_attempt = 0
            host.last_delay = None
            host.next_reconnect_t = 0.0  # probe on the next tick
        tel = telemetry_mod.current()
        tel.counter("serving_fleet_joins_total").inc()
        tel.gauge("serving_fleet_hosts_count").set(len(self.hosts))
        tel.event(
            "serving.fleet_host_joined", host=host.hid, url=url,
        )
        return host.hid

    # -- observability -----------------------------------------------------
    def readiness(self) -> tuple[bool, str]:
        healthy = self.healthy_count
        if not self._started:
            return False, "not started"
        if healthy == 0:
            return False, "no healthy host"
        return True, "ok"

    def healthz(self) -> dict:
        with self._lock:
            hosts = [
                {
                    "hid": h.hid,
                    "url": h.base_url,
                    "state": h.state,
                    "inflight": h.inflight,
                    "probe_failures": h.probe_failures,
                    "reconnect_attempt": h.reconnect_attempt,
                    "reconnects": h.reconnects,
                    "down_reason": h.down_reason,
                    "requests": h.requests,
                }
                for h in self.hosts
            ]
        healthy = sum(1 for h in hosts if h["state"] == "healthy")
        active = sum(
            1 for h in hosts if h["state"] not in ("removed",)
        )
        return {
            "status": (
                "stopped" if not self._started
                else "down" if healthy == 0
                else "degraded" if healthy < active
                else "ok"
            ),
            "hosts": hosts,
            "healthy_hosts": healthy,
        }

    def stats(self) -> dict:
        out = self.healthz()
        out["pending"] = self._queue.qsize()
        out["max_pending"] = self.max_pending
        return out


# ---------------------------------------------------------------------------
# QuotaCoordinator: fleet budgets -> per-host leases
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class FleetBudget:
    """One tenant's fleet-wide admission budget.

    ``burst_s`` sizes lease bursts in seconds-at-rate (a lease of R rps
    carries ``max(1, R * burst_s)`` bucket tokens); ``min_share`` is
    the fraction of the budget reserved as an equal floor across live
    hosts, so a host with zero observed demand still holds a nonzero
    lease and can admit the first requests of a traffic shift without
    waiting a renewal cycle."""

    tenant: str
    rate_rps: float
    burst_s: float = 1.0
    min_share: float = 0.1

    def __post_init__(self):
        if self.rate_rps < 0:
            raise ValueError(
                f"rate_rps must be >= 0, got {self.rate_rps}"
            )
        if not (0.0 <= self.min_share <= 1.0):
            raise ValueError(
                f"min_share must be in [0, 1], got {self.min_share}"
            )


@dataclasses.dataclass(frozen=True)
class Lease:
    """One host's short-lived slice of a tenant's fleet budget."""

    tenant: str
    host_id: str
    rate_rps: float
    burst: float
    seq: int
    #: coordinator-clock expiry; a host that stops renewing stops being
    #: counted against the budget after this instant (reclaim-on-death).
    expires_at: float
    window_s: float


@dataclasses.dataclass
class _Grant:
    rate_rps: float
    demand_rps: float
    expires_at: float


class QuotaCoordinator:
    """Per-tenant fleet budgets carved into per-host rate leases.

    Invariant: for each tenant, the sum of UNEXPIRED outstanding grants
    never exceeds the budget.  A renewal computes the host's demand-
    proportional target share but only grants what the budget minus
    every other live grant leaves — so rebalancing converges within one
    renewal round per host without ever over-committing, and a dead
    host's share is reclaimable the moment its lease expires.

    The coordinator is deliberately a plain object with an injectable
    clock: in-process today (tests, selfcheck, single-box fleets), an
    RPC service later — the lease algebra does not change.
    """

    def __init__(
        self,
        budgets,
        lease_ttl_s: float = 1.0,
        clock: Callable[[], float] = time.monotonic,
    ):
        if isinstance(budgets, dict):
            budgets = [
                FleetBudget(tenant=t, rate_rps=float(r))
                for t, r in budgets.items()
            ]
        self.budgets: dict[str, FleetBudget] = {
            b.tenant: b for b in budgets
        }
        self.lease_ttl_s = float(lease_ttl_s)
        self._clock = clock
        self._lock = sanitizers.tracked(
            threading.Lock(), "serving.quota_coordinator"
        )
        #: tenant -> host_id -> _Grant
        self._grants: dict[str, dict[str, _Grant]] = {
            b.tenant: {} for b in self.budgets.values()
        }
        self._seq = 0
        self.renewals = 0
        self.reclaims = 0
        self.rebalances = 0

    def renew(
        self, host_id: str, demands: Optional[dict] = None
    ) -> dict[str, Lease]:
        """Grant/refresh ``host_id``'s leases for every budgeted tenant.

        ``demands`` maps tenant -> this host's observed offered rate
        (rps); missing tenants renew at zero demand (they still hold
        the min-share floor).  Returns tenant -> :class:`Lease`."""
        demands = demands or {}
        now = self._clock()
        tel = telemetry_mod.current()
        leases: dict[str, Lease] = {}
        with self._lock:
            self._seq += 1
            self.renewals += 1
            seq = self._seq
            for tenant, budget in self.budgets.items():
                grants = self._grants[tenant]
                # Reclaim leases whose hosts stopped renewing: their
                # rate goes back into the grantable pool right here.
                for h in list(grants):
                    if h != host_id and grants[h].expires_at <= now:
                        del grants[h]
                        self.reclaims += 1
                        tel.counter(
                            "serving_fleet_lease_reclaims_total"
                        ).inc()
                demand = max(0.0, float(demands.get(tenant, 0.0)))
                live = set(grants) | {host_id}
                dem = {
                    h: (demand if h == host_id
                        else grants[h].demand_rps)
                    for h in live
                }
                target = self._target_share(budget, dem, host_id)
                outstanding = sum(
                    g.rate_rps for h, g in grants.items()
                    if h != host_id
                )
                rate = max(
                    0.0, min(target, budget.rate_rps - outstanding)
                )
                previous = grants.get(host_id)
                if previous is not None and abs(
                    previous.rate_rps - rate
                ) > 1e-9:
                    self.rebalances += 1
                    tel.counter(
                        "serving_fleet_lease_rebalance_total"
                    ).inc()
                grants[host_id] = _Grant(
                    rate_rps=rate,
                    demand_rps=demand,
                    expires_at=now + self.lease_ttl_s,
                )
                leases[tenant] = Lease(
                    tenant=tenant,
                    host_id=host_id,
                    rate_rps=rate,
                    burst=max(1.0, rate * budget.burst_s),
                    seq=seq,
                    expires_at=now + self.lease_ttl_s,
                    window_s=self.lease_ttl_s,
                )
            outstanding_total = sum(
                g.rate_rps
                for grants in self._grants.values()
                for g in grants.values()
            )
        tel.counter("serving_fleet_lease_grants_total").inc(len(leases))
        tel.gauge("serving_fleet_lease_outstanding_rps").set(
            round(outstanding_total, 3)
        )
        return leases

    def restore_grant(
        self,
        tenant: str,
        host_id: str,
        rate_rps: float,
        demand_rps: float,
        expires_at: float,
    ) -> None:
        """Seed one grant from a durable record (the cluster tier's
        coordinator journal): a freshly-elected coordinator replica
        replays the previous leader's journaled grants through here, so
        its budget arithmetic starts from the SAME outstanding set the
        old leader promised — failover never double-grants a budget
        slice that is still live on some host.  Expired grants may be
        restored too; the next renewal reclaims them normally."""
        if tenant not in self.budgets:
            return  # a tenant the new configuration no longer budgets
        with self._lock:
            self._grants[tenant][str(host_id)] = _Grant(
                rate_rps=float(rate_rps),
                demand_rps=float(demand_rps),
                expires_at=float(expires_at),
            )

    @staticmethod
    def _target_share(
        budget: FleetBudget, demands: dict, host_id: str
    ) -> float:
        """Demand-proportional share with an equal min-share floor."""
        n = len(demands)
        floor = budget.rate_rps * budget.min_share / n
        variable = budget.rate_rps - floor * n
        total_demand = sum(demands.values())
        if total_demand <= 0.0:
            return budget.rate_rps / n  # no signal: equal split
        return floor + variable * demands[host_id] / total_demand

    def stats(self) -> dict:
        now = self._clock()
        with self._lock:
            tenants = {}
            for tenant, budget in self.budgets.items():
                grants = self._grants[tenant]
                tenants[tenant] = {
                    "budget_rps": budget.rate_rps,
                    "outstanding_rps": round(
                        sum(g.rate_rps for g in grants.values()), 3
                    ),
                    "hosts": {
                        h: {
                            "rate_rps": round(g.rate_rps, 3),
                            "demand_rps": round(g.demand_rps, 3),
                            "expired": g.expires_at <= now,
                        }
                        for h, g in grants.items()
                    },
                }
            return {
                "lease_ttl_s": self.lease_ttl_s,
                "renewals": self.renewals,
                "reclaims": self.reclaims,
                "rebalances": self.rebalances,
                "tenants": tenants,
            }


class LeaseClient:
    """Host-side lease agent: measure demand, renew, apply — or degrade.

    ``poll_once()`` is the whole protocol: read this host's per-tenant
    demand since the last poll (``service.demand_snapshot`` deltas),
    call ``coordinator.renew`` through the ``quota.lease`` chaos seam,
    and apply each granted lease to the host's token buckets
    (``service.set_tenant_quota``).  On ANY renewal failure — chaos
    fault, scripted ``partitioned`` flag, a real RPC error once the
    coordinator is remote — the client keeps the LAST applied lease:
    enforcement never becomes unlimited (buckets keep their rates) and
    never zero (the rates stay what they were), so a partition bounds
    fleet over-admission to one lease window.

    ``start()`` runs the loop on a daemon thread every
    ``renew_interval_s`` (default: half the coordinator's lease TTL, so
    one missed beat never expires a healthy host's lease); tests call
    ``poll_once()`` directly and never sleep."""

    def __init__(
        self,
        host_id: str,
        coordinator: QuotaCoordinator,
        service,
        renew_interval_s: Optional[float] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.host_id = str(host_id)
        self.coordinator = coordinator
        self.service = service
        self.renew_interval_s = (
            coordinator.lease_ttl_s / 2.0
            if renew_interval_s is None else float(renew_interval_s)
        )
        self._clock = clock
        #: scripted partition switch (the quota_partition scenario).
        self.partitioned = False
        self.leases: dict[str, Lease] = {}
        self.stale = False
        self.renewals = 0
        self.renew_failures = 0
        self._prev_demand: dict[str, int] = {}
        self._prev_t: Optional[float] = None
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the protocol ------------------------------------------------------
    def _demand_rates(self, now: float) -> dict[str, float]:
        counts = self.service.demand_snapshot()
        if self._prev_t is None:
            rates = {t: 0.0 for t in counts}
        else:
            dt = max(1e-6, now - self._prev_t)
            rates = {
                t: max(0, c - self._prev_demand.get(t, 0)) / dt
                for t, c in counts.items()
            }
        self._prev_demand = counts
        self._prev_t = now
        return rates

    def poll_once(self) -> bool:
        """One renewal round; returns True when the lease refreshed.
        False = degraded to the last lease (partition contract)."""
        now = self._clock()
        rates = self._demand_rates(now)
        tel = telemetry_mod.current()
        try:
            # The partition seam: a fault here is this host losing its
            # network path to the coordinator (docs/robustness.md).
            chaos_mod.maybe_fail("quota.lease", host=self.host_id)
            if self.partitioned:
                raise RuntimeError(
                    "UNAVAILABLE: quota coordinator unreachable "
                    "(scripted partition)"
                )
            leases = self.coordinator.renew(self.host_id, rates)
        except Exception:  # noqa: BLE001 — degrade, never die
            self.renew_failures += 1
            if not self.stale:
                self.stale = True
                tel.event(
                    "serving.fleet_lease_stale", host=self.host_id,
                    failures=self.renew_failures,
                )
            tel.counter(
                "serving_fleet_lease_renew_failures_total"
            ).inc()
            return False
        for tenant, lease in leases.items():
            self.service.set_tenant_quota(
                tenant, lease.rate_rps, lease.burst
            )
        if self.stale:
            tel.event(
                "serving.fleet_lease_recovered", host=self.host_id
            )
        self.leases = leases
        self.stale = False
        self.renewals += 1
        return True

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "LeaseClient":
        if self._thread is None:
            self._stop_evt.clear()
            self._thread = threading.Thread(
                target=self._loop,
                name=f"lease-client-{self.host_id}", daemon=True,
            )
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop_evt.set()
        thread = self._thread
        self._thread = None
        if thread is not None:
            thread.join(timeout=timeout)

    def _loop(self) -> None:
        # First renewal immediately: a host should hold a real lease
        # before its first request, not one interval later.
        while True:
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — the agent must survive
                pass
            if self._stop_evt.wait(self.renew_interval_s):
                return

    def stats(self) -> dict:
        return {
            "host_id": self.host_id,
            "stale": self.stale,
            "partitioned": self.partitioned,
            "renewals": self.renewals,
            "renew_failures": self.renew_failures,
            "leases": {
                t: {
                    "rate_rps": round(lease.rate_rps, 3),
                    "burst": round(lease.burst, 3),
                    "seq": lease.seq,
                }
                for t, lease in self.leases.items()
            },
        }


# ---------------------------------------------------------------------------
# LocalHost: one in-process host behind its own HTTP listener
# ---------------------------------------------------------------------------

class LocalHost:
    """A ``ScoringService`` behind its own HTTP listener — one fleet
    host, in-process.  ``kill()`` tears the listener down abruptly (new
    connections refuse — what a crashed host looks like from the
    router); ``restart()`` rebinds the SAME port, so the router's
    reconnect probes find the host again without reconfiguration;
    ``stop()`` is the graceful full shutdown.  The service is started
    on first ``start()`` and stopped only by ``stop()`` — a killed
    host's service survives, exactly like a host whose network died
    but whose process did not."""

    def __init__(self, host_id: str, service, host: str = "127.0.0.1"):
        from photon_ml_tpu.serving.service import ScoringService

        if not isinstance(service, ScoringService):
            raise TypeError(
                "LocalHost wraps a ScoringService; got "
                f"{type(service).__name__}"
            )
        self.host_id = str(host_id)
        self.service = service
        self._host = host
        self._server = None
        self._thread: Optional[threading.Thread] = None
        self._service_started = False
        self.port: Optional[int] = None
        self.lease_client: Optional[LeaseClient] = None

    @property
    def base_url(self) -> str:
        if self.port is None:
            raise RuntimeError("host is not started")
        return f"http://{self._host}:{self.port}"

    def start(self) -> "LocalHost":
        from photon_ml_tpu.serving.service import start_http_server

        if self._server is not None:
            return self
        if not self._service_started:
            self.service.start()
            self._service_started = True
        self._server, self._thread = start_http_server(
            self.service, host=self._host, port=self.port or 0
        )
        self.port = self._server.server_address[1]
        return self

    def attach_lease_client(
        self, coordinator: QuotaCoordinator, **kwargs
    ) -> LeaseClient:
        """Wire this host into a coordinator's lease protocol (started
        by the caller, or driven manually via ``poll_once``)."""
        self.lease_client = LeaseClient(
            self.host_id, coordinator, self.service, **kwargs
        )
        return self.lease_client

    def kill(self) -> None:
        """Abrupt listener teardown — the scripted host crash."""
        server, thread = self._server, self._thread
        self._server, self._thread = None, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if thread is not None:
            thread.join(timeout=5.0)
        telemetry_mod.current().event(
            "serving.fleet_host_killed", host=self.host_id,
            port=self.port,
        )

    def restart(self) -> "LocalHost":
        """Rebind the listener on the same port (the 'host came back'
        half of the host_kill scenario)."""
        return self.start()

    def stop(self, timeout: float = 5.0) -> None:
        if self.lease_client is not None:
            self.lease_client.stop(timeout=timeout)
        self.kill()
        if self._service_started:
            self.service.stop()
            self._service_started = False

    def __enter__(self) -> "LocalHost":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False
