"""Online scoring runtime: pre-compiled bucket kernels + hot/cold entities.

``ScoringRuntime`` loads a saved GLM or GAME model ONCE and turns it into
a request-path scorer:

- **Bucket ladder** — the jitted batch kernel (serving/kernels.py) is
  compiled ahead of time at a ladder of padded batch sizes (powers of two
  up to ``max_batch_size``), warmed through
  :func:`photon_ml_tpu.utils.compile_cache.warmup` at startup, so the
  request path never compiles.  A batch of B rows pads to the smallest
  bucket ≥ B; padding rows are zeros and slot 0 (exact no-ops).
- **Hot/cold split** — each random-effect coordinate's per-entity
  coefficients live host-side as the model's sparse table (millions of
  entities), while an LRU hot set of ``hot_entities`` dense rows stays
  resident on device as a ``(H+1, D)`` table (row 0 reserved zero).  Hot
  rows gather ON DEVICE by slot; the cold tail falls back to host-side
  gathers (:func:`~photon_ml_tpu.serving.kernels.dense_coefficient_rows`)
  uploaded with the batch, then promotes into the hot set (evicting LRU)
  for the next request.  ``table[slot] + cold`` keeps hot and cold rows
  bit-identical.

All mutation (LRU order, hot-table updates) happens on the dispatch
thread — the MicroBatcher owns scoring — so the runtime needs no locks;
``parse_request`` is read-only and safe from any request thread.

**Graceful degradation** — the runtime survives losing its accelerator:
a device-path failure the watchdog vocabulary classifies as transient
(``UNAVAILABLE``/device lost/...) flips the runtime into DEGRADED mode —
every batch scores through a pure-numpy host cold path (same margins and
mean link, no device touch, correct scores at host float tolerance) and
requests keep succeeding with zero errors.  A per-runtime circuit
breaker (:class:`photon_ml_tpu.chaos.CircuitBreaker`, closed → open →
half-open) guards re-promotion: after ``breaker_cooldown_s`` one batch
probes the device path; success re-promotes (degraded flag clears), a
failed probe re-opens the breaker and degraded serving continues.  The
``degraded`` flag rides ``/healthz`` and ``/stats``.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import threading
from typing import Optional, Sequence

import numpy as np

from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.chaos.breaker import CircuitBreaker
from photon_ml_tpu.game.model import (
    FixedEffectModel,
    GameModel,
    RandomEffectModel,
)
from photon_ml_tpu.models.glm import GeneralizedLinearModel
from photon_ml_tpu.ops import losses as losses_lib
from photon_ml_tpu.serving import kernels as kernels_lib
from photon_ml_tpu import telemetry as telemetry_mod


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Serving-side model knobs (batching knobs live on BatcherConfig)."""

    #: top of the bucket ladder; also the largest batch one dispatch scores.
    max_batch_size: int = 64
    #: per-coordinate LRU hot-set capacity (dense rows resident on device).
    hot_entities: int = 1024
    #: compile every bucket at startup (skip only in tests that assert on
    #: compile behavior themselves).
    warmup: bool = True
    #: seconds the circuit breaker stays OPEN after a device-path failure
    #: before admitting one half-open probe batch (re-promotion guard).
    breaker_cooldown_s: float = 5.0
    #: consecutive device-path failures before the breaker trips.
    breaker_failure_threshold: int = 1
    #: score through the fused single-round-trip kernel (two uploads +
    #: one readback per batch, any model structure) instead of the
    #: composed per-coordinate kernel.  Scores are bitwise identical
    #: either way (kernels.build_fused_bucket_kernel); the composed
    #: path remains for A/B benchmarking and as the conservative knob.
    fused: bool = True


def _host_mean(task: str, margins: np.ndarray) -> np.ndarray:
    """The mean link evaluated with host numpy (degraded-mode scoring):
    the same inverse links ops/losses.py defines, no device touch.  The
    logistic branch mirrors jax.nn.sigmoid's numerically-stable split so
    large |margin| rows agree with the device path."""
    if task == "logistic":
        out = np.empty_like(margins, np.float32)
        pos = margins >= 0
        out[pos] = 1.0 / (1.0 + np.exp(-margins[pos]))
        em = np.exp(margins[~pos])
        out[~pos] = em / (1.0 + em)
        return out
    if task == "poisson":
        return np.exp(margins).astype(np.float32)
    # squared / smoothed_hinge: identity link.
    return margins.astype(np.float32)


#: request priorities the admission controller understands; "low" work is
#: the first tier shed under load (serving/batcher.py).
PRIORITIES = ("low", "normal", "high")


@dataclasses.dataclass
class Row:
    """One parsed scoring request."""

    features: dict  # shard name -> np.float32 (D,) or None (all zeros)
    ids: dict  # entity-key name -> str entity id (or absent)
    offset: float = 0.0
    timeout_ms: Optional[float] = None
    priority: str = "normal"  # one of PRIORITIES
    #: tenant id for multi-tenant admission/routing (serving/tenancy.py);
    #: None rides the default tenant's partition and route.
    tenant: Optional[str] = None


class RequestParser:
    """Stateless request validation: shard dims + saved index maps — the
    ONLY runtime state request parsing reads, split out so the
    process-backed worker pool can parse in the parent (routing, probe
    rows) without holding a local :class:`ScoringRuntime`
    (serving/procpool.py).  ``ScoringRuntime.parse_request`` delegates
    here, so both serving modes validate identically."""

    def __init__(
        self, shard_dims: dict, index_maps: Optional[dict] = None
    ):
        self.shard_dims = dict(shard_dims)
        self.index_maps = index_maps or {}

    @classmethod
    def for_model(
        cls, model: GameModel, index_maps: Optional[dict] = None
    ) -> "RequestParser":
        """Shard dims straight off the model's coordinates — the same
        derivation ScoringRuntime.__init__ performs."""
        shard_dims: dict[str, int] = {}
        for sub in model.models.values():
            if isinstance(sub, FixedEffectModel):
                shard_dims[sub.feature_shard] = int(
                    np.asarray(sub.model.coefficients.means).shape[0]
                )
            elif isinstance(sub, RandomEffectModel):
                shard_dims[sub.feature_shard] = int(sub.n_features)
            else:
                raise TypeError(f"unsupported coordinate type: {type(sub)}")
        return cls(shard_dims, index_maps)

    def parse(self, obj: dict) -> "Row":
        """Validate one JSON-shaped request into a :class:`Row`.

        ``dense``: shard → full-width float list.  ``features``: shard →
        named entries (``{"name", "term", "value"}`` dicts or
        ``[name, term, value]`` triples) resolved through the saved index
        map — unseen features drop, exactly like batch scoring.
        """
        if not isinstance(obj, dict):
            raise ValueError("request must be a JSON object")
        features: dict = {}
        for shard, vec in (obj.get("dense") or {}).items():
            dim = self.shard_dims.get(shard)
            if dim is None:
                raise ValueError(f"unknown feature shard {shard!r}")
            arr = np.asarray(vec, np.float32)
            if arr.shape != (dim,):
                raise ValueError(
                    f"shard {shard!r} expects {dim} features, got "
                    f"{arr.shape}"
                )
            features[shard] = arr
        for shard, entries in (obj.get("features") or {}).items():
            dim = self.shard_dims.get(shard)
            if dim is None:
                raise ValueError(f"unknown feature shard {shard!r}")
            imap = self.index_maps.get(shard)
            if imap is None:
                raise ValueError(
                    f"shard {shard!r} has no saved index map; send "
                    "'dense' features"
                )
            from photon_ml_tpu.data.index_map import feature_key

            arr = features.get(shard)
            if arr is None:
                arr = np.zeros(dim, np.float32)
            for e in entries:
                if isinstance(e, dict):
                    name, term, value = (
                        e.get("name"), e.get("term", ""), e.get("value"),
                    )
                else:
                    name, term, value = e
                idx = imap.get_index(feature_key(str(name), str(term or "")))
                if idx >= 0:
                    arr[idx] = np.float32(value)
            features[shard] = arr
        ids = {}
        for key, value in (obj.get("ids") or {}).items():
            if value is not None:
                ids[str(key)] = str(value)
        timeout = obj.get("timeout_ms")
        priority = obj.get("priority", "normal")
        if priority not in PRIORITIES:
            raise ValueError(
                f"priority must be one of {PRIORITIES}, got {priority!r}"
            )
        tenant = obj.get("tenant")
        if tenant is not None and not isinstance(tenant, str):
            raise ValueError(
                f"tenant must be a string, got {type(tenant).__name__}"
            )
        return Row(
            features=features,
            ids=ids,
            offset=float(obj.get("offset") or 0.0),
            timeout_ms=None if timeout is None else float(timeout),
            priority=priority,
            tenant=tenant,
        )

    def probe_row(self) -> "Row":
        """A minimal valid request (offset-only) — what health probes
        and swap verification score."""
        return self.parse({})


class _HotTable:
    """LRU hot set of dense per-entity coefficient rows, device-resident.

    Slot 0 is the reserved zero row (cold / unknown / padding); slots
    1..capacity hold entities in LRU order.  Eviction is O(1)
    (OrderedDict), inserts are one ``at[slot].set`` device update.
    """

    def __init__(self, capacity: int, dim: int):
        import jax.numpy as jnp

        self.capacity = int(capacity)
        self.dim = int(dim)
        self.table = jnp.zeros((self.capacity + 1, self.dim), jnp.float32)
        self._slots: "collections.OrderedDict[object, int]" = (
            collections.OrderedDict()
        )
        self._free = list(range(self.capacity, 0, -1))
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0

    def lookup(self, key) -> int:
        """Hot slot for ``key`` (marks it most-recently-used), 0 if cold."""
        slot = self._slots.get(key)
        if slot is None:
            return 0
        self._slots.move_to_end(key)
        self.hits += 1
        return slot

    def insert(self, key, dense_row: np.ndarray) -> None:
        """Promote ``key``; evicts the least-recently-used entity when full."""
        if self.capacity == 0 or key in self._slots:
            return
        if self._free:
            slot = self._free.pop()
        else:
            _, slot = self._slots.popitem(last=False)
            self.evictions += 1
        import jax.numpy as jnp

        self.table = self.table.at[slot].set(jnp.asarray(dense_row))
        self._slots[key] = slot
        self.inserts += 1

    @property
    def size(self) -> int:
        return len(self._slots)

    def hot_keys(self) -> list:
        """LRU→MRU order (test/diagnostic view)."""
        return list(self._slots)


@dataclasses.dataclass
class _FixedCoord:
    name: str
    shard: str
    means: object  # jnp (D,)
    host_means: object = None  # np.float32 (D,) — the degraded cold path


@dataclasses.dataclass
class _RandomCoord:
    name: str
    shard: str
    entity_key: str
    model: RandomEffectModel
    hot: _HotTable
    unknown: int = 0


class ScoringRuntime:
    """A loaded model, compiled and warmed for the online request path."""

    def __init__(
        self,
        model: GameModel,
        index_maps: Optional[dict] = None,
        config: Optional[RuntimeConfig] = None,
    ):
        import jax.numpy as jnp

        self.config = config or RuntimeConfig()
        if self.config.max_batch_size < 1:
            raise ValueError("max_batch_size must be >= 1")
        # Version identity for the hot-swap machinery (serving/swap.py):
        # the initially-loaded model is version 1; every successful swap
        # stamps a strictly greater number.  ``ready`` is the READINESS
        # half of the health split (/readyz): False until the bucket
        # ladder is warm, so a load balancer never routes at a runtime
        # that would compile on the request path.
        self.model_version = 1
        self.model_path: Optional[str] = None
        self.ready = False
        self.model = model
        self.index_maps = index_maps or {}
        self.task = model.task
        self._mean_fn = losses_lib.get(model.task).mean_fn
        self.fixed: list[_FixedCoord] = []
        self.random: list[_RandomCoord] = []
        self.shard_dims: dict[str, int] = {}
        for name, sub in model.models.items():
            if isinstance(sub, FixedEffectModel):
                w = np.asarray(sub.model.coefficients.means, np.float32)
                self.fixed.append(
                    _FixedCoord(name, sub.feature_shard, jnp.asarray(w), w)
                )
                self.shard_dims[sub.feature_shard] = w.shape[0]
            elif isinstance(sub, RandomEffectModel):
                self.random.append(_RandomCoord(
                    name, sub.feature_shard, sub.entity_key, sub,
                    _HotTable(self.config.hot_entities, sub.n_features),
                ))
                self.shard_dims[sub.feature_shard] = sub.n_features
            else:
                raise TypeError(f"unsupported coordinate type: {type(sub)}")
        if not self.fixed and not self.random:
            raise ValueError("model has no coordinates to serve")
        self._parser = RequestParser(self.shard_dims, self.index_maps)
        self.buckets = self._bucket_ladder(self.config.max_batch_size)
        if self.config.fused:
            self._kernel = kernels_lib.build_fused_bucket_kernel(
                self._mean_fn
            )
        else:
            self._kernel = kernels_lib.build_bucket_kernel(self._mean_fn)
        #: packed-buffer width for the fused kernel: offset column +
        #: fixed feature blocks + (features, cold) block pairs per
        #: random coordinate (kernels.build_fused_bucket_kernel).
        self._packed_width = 1 + sum(
            int(np.asarray(c.host_means).shape[0]) for c in self.fixed
        ) + sum(2 * c.hot.dim for c in self.random)
        self.batches = 0
        self.rows_scored = 0
        self.warmup_compiles = 0
        # stats snapshot vs dispatch thread
        self._lock = sanitizers.tracked(
            threading.Lock(), "serving.runtime"
        )
        # Graceful degradation: device-lost flips scoring onto the host
        # cold path; the breaker guards re-promotion (module docstring).
        self.degraded = False
        self.breaker = CircuitBreaker(
            cooldown_seconds=self.config.breaker_cooldown_s,
            failure_threshold=self.config.breaker_failure_threshold,
        )
        from photon_ml_tpu.utils.watchdog import RetryPolicy

        self._fault_policy = RetryPolicy()  # classification only
        self.degraded_batches = 0
        self.device_failures = 0
        self.repromotions = 0
        # HBM accounting: the hot tables are the serving path's device-
        # resident working set — (capacity+1) x dim f32 per random
        # coordinate, allocated up front (LRU inserts overwrite rows,
        # they never grow the table).
        self.hot_table_bytes = sum(
            (c.hot.capacity + 1) * c.hot.dim * 4 for c in self.random
        )
        telemetry_mod.current().gauge(
            "hbm_serving_hot_table_bytes"
        ).set(self.hot_table_bytes)
        if self.config.warmup:
            self.warm_up()
        self.ready = True

    # -- construction ------------------------------------------------------
    @staticmethod
    def _bucket_ladder(max_batch: int) -> list[int]:
        ladder = []
        b = 1
        while b < max_batch:
            ladder.append(b)
            b *= 2
        ladder.append(max_batch)
        return ladder

    @classmethod
    def from_glm_model(
        cls,
        model: GeneralizedLinearModel,
        index_map=None,
        shard: str = "features",
        config: Optional[RuntimeConfig] = None,
    ) -> "ScoringRuntime":
        """Serve a plain GLM as a one-fixed-coordinate GAME model."""
        game = GameModel(
            models={"fixed": FixedEffectModel(model, shard)},
            task=model.task,
        )
        imaps = {shard: index_map} if index_map is not None else {}
        return cls(game, imaps, config)

    @staticmethod
    def load_model(path: str) -> tuple[GameModel, dict]:
        """Read a saved model off disk: a GAME model directory (either
        the directory holding ``metadata.json`` or a driver output dir
        with a ``models/`` subdir) or a GLM ``.avro`` file.  Returns
        ``(GameModel, index_maps)`` — fingerprint sidecars are verified
        by the stores (a tampered payload raises before anything is
        served).  The hot-swap path loads ONCE through here and builds
        one runtime per replica from the shared host-side model."""
        if os.path.isdir(path):
            from photon_ml_tpu.io.game_store import load_game_model

            if not os.path.exists(os.path.join(path, "metadata.json")):
                nested = os.path.join(path, "models")
                if os.path.exists(os.path.join(nested, "metadata.json")):
                    path = nested
            return load_game_model(path)
        from photon_ml_tpu.io.model_store import load_glm_model

        glm, imap = load_glm_model(path)
        game = GameModel(
            models={"fixed": FixedEffectModel(glm, "features")},
            task=glm.task,
        )
        return game, {"features": imap}

    @classmethod
    def load(
        cls, path: str, config: Optional[RuntimeConfig] = None
    ) -> "ScoringRuntime":
        """Load a saved model (see :meth:`load_model`) into a runtime."""
        model, index_maps = cls.load_model(path)
        runtime = cls(model, index_maps, config)
        runtime.model_path = path
        return runtime

    def _kernel_geometry(self) -> tuple:
        """Everything the compiled bucket ladder is shaped by: task
        (mean link), bucket sizes, fixed dims, random (dim, capacity)
        pairs.  Two runtimes with equal geometry can share one jitted
        kernel object — and with it the already-compiled ladder."""
        return (
            self.task,
            bool(self.config.fused),
            tuple(self.buckets),
            tuple(int(c.means.shape[0]) for c in self.fixed),
            tuple((c.hot.dim, c.hot.capacity) for c in self.random),
        )

    @classmethod
    def patched(
        cls,
        base: "ScoringRuntime",
        model: GameModel,
        index_maps: Optional[dict] = None,
        config: Optional[RuntimeConfig] = None,
        carry_hot: bool = True,
    ) -> "ScoringRuntime":
        """Build a runtime around ``model`` by CLONING ``base``'s
        compiled identity — the delta-apply fast path (serving/swap.py
        ``swap_delta``).

        A value-only delta never changes kernel geometry (same task,
        dims, bucket ladder), so the new runtime adopts ``base``'s
        jitted kernel object and with it every already-compiled bucket:
        zero compiles, no warmup wall.  The LRU hot sets are then
        carried (:func:`carry_hot_sets`) — every row REBUILT from the
        patched model, never copied from the live device tables (the
        dispatch thread may be mutating those mid-clone).  Geometry
        drift (a config change) falls back to a full warmup; the result
        is correct either way."""
        cfg = config or base.config
        rt = cls(
            model,
            base.index_maps if index_maps is None else index_maps,
            dataclasses.replace(cfg, warmup=False),
        )
        # Restore the caller-visible config: warmup was suppressed only
        # for THIS construction; a replica restarted from this config
        # must still warm its ladder.
        rt.config = cfg
        if rt._kernel_geometry() == base._kernel_geometry():
            rt._kernel = base._kernel
            rt.warmup_compiles = 0
        elif cfg.warmup:
            rt.warm_up()
        if carry_hot:
            carry_hot_sets(base, rt)
        return rt

    # -- warmup ------------------------------------------------------------
    def _abstract_args(self, bucket: int) -> tuple:
        import jax

        f32 = np.float32
        sds = jax.ShapeDtypeStruct
        if self.config.fused:
            packed = sds((bucket, self._packed_width), f32)
            slots = sds((len(self.random), bucket), np.int32)
            fixed_w = tuple(
                sds((int(c.means.shape[0]),), f32) for c in self.fixed
            )
            re_tables = tuple(
                sds((c.hot.capacity + 1, c.hot.dim), f32)
                for c in self.random
            )
            return (packed, slots, fixed_w, re_tables)
        offsets = sds((bucket,), f32)
        fixed_x = tuple(
            sds((bucket, int(c.means.shape[0])), f32) for c in self.fixed
        )
        fixed_w = tuple(sds((int(c.means.shape[0]),), f32) for c in self.fixed)
        re_x = tuple(sds((bucket, c.hot.dim), f32) for c in self.random)
        re_tables = tuple(
            sds((c.hot.capacity + 1, c.hot.dim), f32) for c in self.random
        )
        re_slots = tuple(sds((bucket,), np.int32) for c in self.random)
        re_cold = tuple(sds((bucket, c.hot.dim), f32) for c in self.random)
        return (offsets, fixed_x, fixed_w, re_x, re_tables, re_slots, re_cold)

    def warm_up(self) -> int:
        """Compile the scoring kernel at every bucket shape (no compiles
        on the request path afterwards).  Returns the compile count."""
        from photon_ml_tpu.utils.compile_cache import warmup

        shapes = [self._abstract_args(b) for b in self.buckets]
        self.warmup_compiles = warmup(
            [self._kernel] * len(self.buckets), shapes
        )
        return self.warmup_compiles

    # -- request parsing ---------------------------------------------------
    def parse_request(self, obj: dict) -> Row:
        """Validate one JSON-shaped request into a :class:`Row` — see
        :meth:`RequestParser.parse` (the shared implementation both
        serving modes use)."""
        return self._parser.parse(obj)

    def probe_row(self) -> Row:
        """A minimal valid request (offset-only) — what health probes and
        swap verification score.  Scores 0 margin on any model; the point
        is exercising the whole dispatch → kernel → future path."""
        return self.parse_request({})

    # -- scoring -----------------------------------------------------------
    def bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(
            f"batch of {n} exceeds max_batch_size={self.buckets[-1]}"
        )

    def score_rows(self, rows: Sequence[Row]) -> tuple[np.ndarray, np.ndarray]:
        """Score a batch; survives a lost device.

        Returns ``(margins, means)`` float32 arrays of ``len(rows)``.
        Dispatch-thread only (mutates the LRU hot sets and the breaker).

        The healthy path is the padded bucket kernel
        (:meth:`_score_rows_device`).  A transient device failure (the
        watchdog's UNAVAILABLE/device-lost vocabulary) degrades THIS
        batch — and every batch until the breaker re-promotes — onto the
        pure-host cold path (:meth:`_score_rows_host`): requests keep
        succeeding, the ``degraded`` flag rides /healthz and /stats.
        Non-transient failures (bad batch size, programming errors)
        propagate unchanged — degrading on those would mask real bugs.
        """
        if self.degraded and not self.breaker.allow_request():
            return self._score_rows_host(rows)
        try:
            margins, means = self._score_rows_device(rows)
        except Exception as exc:  # noqa: BLE001 — classified below
            if not self._fault_policy.classify(exc).transient:
                raise
            self._note_device_failure(exc)
            return self._score_rows_host(rows)
        if self.degraded:
            self._note_repromotion()
        return margins, means

    def _note_device_failure(self, exc: BaseException) -> None:
        tel = telemetry_mod.current()
        self.breaker.record_failure()
        self.device_failures += 1
        tel.counter("serving_device_failures_total").inc()
        tel.gauge("serving_degraded").set(1)
        if not self.degraded:
            self.degraded = True
            tel.event(
                "serving.degraded",
                error=f"{type(exc).__name__}: {exc}"[:200],
                breaker=self.breaker.state,
            )

    def _note_repromotion(self) -> None:
        tel = telemetry_mod.current()
        self.breaker.record_success()
        self.degraded = False
        self.repromotions += 1
        tel.counter("serving_repromotions_total").inc()
        tel.gauge("serving_degraded").set(0)
        tel.event("serving.repromoted", breaker=self.breaker.state)

    def _score_rows_host(
        self, rows: Sequence[Row]
    ) -> tuple[np.ndarray, np.ndarray]:
        """Degraded-mode scoring: pure numpy, zero device touches.

        Same margin arithmetic as the kernel (offset + Σ fixed x·w +
        Σ random-effect x·row) and the same mean link, evaluated with
        host numpy — scores agree with the device path to host float
        tolerance (the device kernel's reduce order differs in the last
        ulp; bit parity is the HEALTHY path's contract, availability is
        this one's).  The LRU hot sets are deliberately untouched: their
        device tables may be gone with the device."""
        tel = telemetry_mod.current()
        n = len(rows)
        margins = np.zeros(n, np.float32)
        for i, row in enumerate(rows):
            margins[i] = np.float32(row.offset)
        for c in self.fixed:
            for i, row in enumerate(rows):
                vec = row.features.get(c.shard)
                if vec is not None:
                    margins[i] += np.float32(np.dot(vec, c.host_means))
        for c in self.random:
            for i, row in enumerate(rows):
                key = row.ids.get(c.entity_key)
                if key is None:
                    continue
                entry = c.model.coefficients.get(key)
                if entry is None:
                    c.unknown += 1
                    tel.counter("serving_unknown_entities_total").inc()
                    continue
                vec = row.features.get(c.shard)
                if vec is None:
                    continue
                dense = kernels_lib.dense_coefficient_rows(c.model, [key])[0]
                margins[i] += np.float32(np.dot(vec, dense))
        means = _host_mean(self.task, margins)
        with self._lock:
            self.batches += 1
            self.rows_scored += n
            self.degraded_batches += 1
        tel.counter("serving_batches_total").inc()
        tel.counter("serving_rows_scored_total").inc(n)
        tel.counter("serving_degraded_batches_total").inc()
        return margins, means

    def _score_rows_device(
        self, rows: Sequence[Row]
    ) -> tuple[np.ndarray, np.ndarray]:
        """The healthy path: the padded bucket kernel (bit-parity
        contract).  Dispatch-thread only (mutates the LRU hot sets)."""
        import jax.numpy as jnp

        # The device-lost seam: a scripted fault here exercises the whole
        # degrade → breaker → re-promote machinery above.
        chaos_mod.maybe_fail("serving.device", rows=len(rows))
        n = len(rows)
        bucket = self.bucket_for(n)
        tel = telemetry_mod.current()

        def fill_shard(dst: np.ndarray, shard: str) -> None:
            for i, row in enumerate(rows):
                vec = row.features.get(shard)
                if vec is not None:
                    dst[i] = vec

        def gather_random(c, slots: np.ndarray, cold: np.ndarray) -> None:
            """Hot-slot lookup + cold-tail host gather for one random
            coordinate, writing into the caller's (bucket,) slots and
            (bucket, dim) cold arrays (fused mode passes views into the
            packed buffer, so the gather lands in place)."""
            pending: dict = {}
            hits_before = c.hot.hits
            for i, row in enumerate(rows):
                key = row.ids.get(c.entity_key)
                if key is None:
                    continue
                slot = c.hot.lookup(key)
                if slot:
                    slots[i] = slot
                    continue
                entry = c.model.coefficients.get(key)
                if entry is None:
                    c.unknown += 1
                    tel.counter("serving_unknown_entities_total").inc()
                    continue
                c.hot.misses += 1
                tel.counter("serving_cold_misses_total").inc()
                vec = pending.get(key)
                if vec is None:
                    vec = kernels_lib.dense_coefficient_rows(
                        c.model, [key]
                    )[0]
                    pending[key] = vec
                    promotions.append((c, key, vec))
                cold[i] = vec
            tel.counter("serving_hot_hits_total").inc(
                c.hot.hits - hits_before
            )

        promotions: list[tuple[_RandomCoord, object, np.ndarray]] = []
        if self.config.fused:
            # Single-round-trip path: every request-side value rides in
            # ONE packed f32 buffer plus one i32 slot matrix (two
            # uploads), and margins+means come back stacked (one
            # readback) — see kernels.build_fused_bucket_kernel.
            packed = np.zeros((bucket, self._packed_width), np.float32)
            all_slots = np.zeros((len(self.random), bucket), np.int32)
            for i, row in enumerate(rows):
                packed[i, 0] = row.offset
            off = 1
            for c in self.fixed:
                d = int(c.means.shape[0])
                fill_shard(packed[:, off:off + d], c.shard)
                off += d
            for j, c in enumerate(self.random):
                d = c.hot.dim
                fill_shard(packed[:, off:off + d], c.shard)
                gather_random(
                    c, all_slots[j], packed[:, off + d:off + 2 * d]
                )
                off += 2 * d
            out = np.asarray(self._kernel(
                jnp.asarray(packed), jnp.asarray(all_slots),
                tuple(c.means for c in self.fixed),
                tuple(c.hot.table for c in self.random),
            ))
            margins = np.asarray(out[0, :n], np.float32)
            means = np.asarray(out[1, :n], np.float32)
        else:
            offsets = np.zeros(bucket, np.float32)
            for i, row in enumerate(rows):
                offsets[i] = row.offset

            def shard_matrix(shard: str, dim: int) -> np.ndarray:
                x = np.zeros((bucket, dim), np.float32)
                fill_shard(x, shard)
                return x

            fixed_x = tuple(
                jnp.asarray(shard_matrix(c.shard, int(c.means.shape[0])))
                for c in self.fixed
            )
            fixed_w = tuple(c.means for c in self.fixed)

            re_x, re_tables, re_slots, re_cold = [], [], [], []
            for c in self.random:
                slots = np.zeros(bucket, np.int32)
                cold = np.zeros((bucket, c.hot.dim), np.float32)
                gather_random(c, slots, cold)
                re_x.append(jnp.asarray(shard_matrix(c.shard, c.hot.dim)))
                re_tables.append(c.hot.table)
                re_slots.append(jnp.asarray(slots))
                re_cold.append(jnp.asarray(cold))

            margins, means = self._kernel(
                jnp.asarray(offsets), fixed_x, fixed_w,
                tuple(re_x), tuple(re_tables), tuple(re_slots),
                tuple(re_cold),
            )
            margins = np.asarray(margins[:n], np.float32)
            means = np.asarray(means[:n], np.float32)

        # Promote the cold tail AFTER this batch (the batch itself scored
        # through the cold path; the next request finds the entity hot).
        for c, key, vec in promotions:
            c.hot.insert(key, vec)
        if promotions:
            tel.gauge("serving_hot_resident_rows").set(
                sum(c.hot.size for c in self.random)
            )
        with self._lock:
            self.batches += 1
            self.rows_scored += n
        tel.counter("serving_batches_total").inc()
        tel.counter("serving_rows_scored_total").inc(n)
        return margins, means

    # -- observability -----------------------------------------------------
    def stats(self) -> dict:
        """Mirrors the telemetry counters, independent of the hub state
        (the /stats endpoint must work with telemetry disabled)."""
        with self._lock:
            batches, rows = self.batches, self.rows_scored
            degraded_batches = self.degraded_batches
        hot = {}
        for c in self.random:
            total = c.hot.hits + c.hot.misses
            hot[c.name] = {
                "capacity": c.hot.capacity,
                "resident": c.hot.size,
                "hits": c.hot.hits,
                "misses": c.hot.misses,
                "hit_rate": (c.hot.hits / total) if total else None,
                "inserts": c.hot.inserts,
                "evictions": c.hot.evictions,
                "unknown_entities": c.unknown,
                "n_entities": c.model.n_entities,
            }
        return {
            "task": self.task,
            "model_version": self.model_version,
            "model_path": self.model_path,
            "ready": self.ready,
            "buckets": list(self.buckets),
            "coordinates": {
                "fixed": [c.name for c in self.fixed],
                "random": [c.name for c in self.random],
            },
            "batches": batches,
            "rows_scored": rows,
            "warmup_compiles": self.warmup_compiles,
            "hot_sets": hot,
            # Degraded-mode observability (docs/robustness.md): the flag
            # /healthz mirrors, the breaker state machine, and how much
            # traffic the host cold path carried.
            "degraded": self.degraded,
            "degraded_batches": degraded_batches,
            "device_failures": self.device_failures,
            "repromotions": self.repromotions,
            "breaker": self.breaker.snapshot(),
        }


def carry_hot_sets(
    old: ScoringRuntime, new: ScoringRuntime, retries: int = 3
) -> int:
    """Seed ``new``'s LRU hot sets from ``old``'s WITHOUT copying device
    rows.  Returns the number of rows carried.

    Only the KEY LISTS are snapshotted from the live runtime; every
    carried row is rebuilt dense from ``new``'s (patched) model and
    inserted in the old LRU→MRU order.  Copying ``old``'s device table
    instead would race the dispatch thread (an eviction between the
    slot snapshot and the table reference would map entity A to entity
    B's row) and would serve STALE rows for delta-changed entities.
    Rebuilt rows cost one host gather per coordinate — and the scoring
    contract (``table[slot] + cold`` keeps hot and cold bit-identical)
    means a raced, slightly-stale KEY list is harmless: it only changes
    which entities start hot, never any score bit.

    ``old``'s OrderedDict may be mutated mid-iteration by its dispatch
    thread (RuntimeError); the snapshot retries, then degrades to an
    empty carry — cold-starting the hot set is always correct."""
    carried = 0
    new_by_name = {c.name: c for c in new.random}
    for oc in old.random:
        nc = new_by_name.get(oc.name)
        if nc is None or nc.hot.capacity == 0:
            continue
        keys: list = []
        for _ in range(max(1, retries)):
            try:
                keys = oc.hot.hot_keys()
                break
            except RuntimeError:  # dict mutated mid-list(); retry
                keys = []
        keys = [k for k in keys if k in nc.model.coefficients]
        if not keys:
            continue
        rows = kernels_lib.dense_coefficient_rows(nc.model, keys)
        for key, row in zip(keys, rows):
            nc.hot.insert(key, row)
        carried += len(keys)
    if carried:
        telemetry_mod.current().gauge("serving_hot_resident_rows").set(
            sum(c.hot.size for c in new.random)
        )
    return carried
