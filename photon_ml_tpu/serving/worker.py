"""Worker process main for process-level serving replicas.

One worker = one OS process with its own fault domain: it attaches the
pool's shared-memory model (zero-copy, checksum-verified —
serving/shm_model.py), runs a private :class:`ScoringRuntime` +
:class:`MicroBatcher`, and speaks the length-prefixed frame protocol
(serving/protocol.py) over the socketpair its parent spawned it with.
A native crash, an OOM kill, or a SIGKILL here costs exactly one
worker; the parent's :class:`~photon_ml_tpu.serving.procpool.
ProcessReplica` fails the in-flight rows with the watchdog's transient
vocabulary and the supervisor resubmits them to a peer.

Frames the worker understands (parent → worker)::

    score         {id, row, tenant?, timeout_ms, bypass}
                                                 → result {id, ok, ...}
    stats         {id}                           → result {id, ok, value}
    swap_prepare  {manifest, runtime_config?, carry_hot?}
                                                 → swap_ready | swap_failed
    swap_commit   {version, tenant?}             → swap_done
    swap_rollback {tenant?}                      → swap_done
    swap_abort    {version}                      (no reply)
    shutdown      {}                             → bye (after drain)

A ``tenant`` on swap_commit routes ONE tenant onto the prepared
runtime (``batcher.set_tenant_route``) without touching the worker's
default serving runtime; each tenant retains exactly one displaced
route for one-step rollback, mirroring the default-route discipline.

and emits unprompted ``heartbeat`` frames every
``heartbeat_interval_s``: liveness + queue depth + model version + a
mergeable :meth:`~photon_ml_tpu.telemetry.core.MetricsRegistry.
transport_snapshot` of the worker's private metrics registry, which the
parent folds into its own registry so /metrics and the admission tiers
keep a pool-wide view.

Swap discipline (the cross-process half of serving/swap.py): prepare
attaches + warms the staged model on a helper thread (the recv loop
keeps answering scores and probes — a seconds-long warmup must not read
as replica death), commit is the same GIL-atomic ``batcher.runtime``
assignment as in-process serving and retains the previous runtime for
exactly one-step rollback.
"""

from __future__ import annotations

import os
import threading
from functools import partial
from typing import Optional, Tuple

import numpy as np

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.serving import shm_model
from photon_ml_tpu.serving.batcher import (
    BatcherConfig,
    DeadlineExceededError,
    MicroBatcher,
    RejectedError,
)
from photon_ml_tpu.serving.protocol import FrameConn
from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime

__all__ = ["worker_main"]


def _pin_platform() -> None:
    """Honor JAX_PLATFORMS before any kernel work: spawned children
    re-import jax, and an installed accelerator plugin would otherwise
    win platform selection even with the env var set."""
    platform = os.environ.get("JAX_PLATFORMS")
    if not platform:
        return
    try:
        import jax

        jax.config.update("jax_platforms", platform)
    except Exception:  # noqa: BLE001 — env pinning is best-effort
        pass


def _error_kind(exc: BaseException) -> str:
    """Collapse a scoring failure to the protocol's error taxonomy so
    the parent can reconstruct the SAME exception type — the supervisor
    type-checks RejectedError/DeadlineExceededError when deciding
    resubmit-vs-fail."""
    if isinstance(exc, RejectedError):
        return "rejected"
    if isinstance(exc, DeadlineExceededError):
        return "deadline"
    return "other"


class _WorkerMain:
    def __init__(
        self,
        conn: FrameConn,
        manifest: dict,
        worker_id: int,
        runtime_config: Optional[RuntimeConfig],
        batcher_config: Optional[BatcherConfig],
        heartbeat_interval_s: float,
    ):
        self._conn = conn
        self._worker_id = int(worker_id)
        self._runtime_config = runtime_config or RuntimeConfig()
        self._batcher_config = batcher_config or BatcherConfig()
        self._heartbeat_interval_s = float(heartbeat_interval_s)
        self._stop = threading.Event()
        self._hb_thread: Optional[threading.Thread] = None
        self._prepare_thread: Optional[threading.Thread] = None
        # Swap state: version -> (runtime, attachment) staged by prepare;
        # exactly one (runtime, attachment, version) retained for
        # one-step rollback after a commit.
        self._prepared: dict = {}
        self._previous: Optional[Tuple] = None
        # Tenant routes: tenant -> (runtime, attachment, version) the
        # batcher dispatches that tenant against, plus the one displaced
        # tuple (or None = "was on the default route") each tenant
        # retains for one-step rollback.
        self._tenant_routes: dict = {}
        self._tenant_prev: dict = {}
        model, attachment = shm_model.attach_model(manifest)
        self._runtime = ScoringRuntime(model, {}, self._runtime_config)
        self._runtime.model_version = int(manifest["version"])
        self._runtime.model_path = manifest.get("path")
        self._attachment = attachment
        self._batcher = MicroBatcher(
            self._runtime, self._batcher_config
        ).start()

    # -- plumbing ----------------------------------------------------------
    def _send(self, message: dict) -> None:
        try:
            self._conn.send(message)
        except Exception:  # noqa: BLE001 — parent gone; wind down
            self._stop.set()

    def _send_result(self, request_id, future) -> None:
        exc = future.exception()
        if exc is None:
            self._send({
                "kind": "result", "id": request_id,
                "ok": True, "value": future.result(),
            })
        else:
            self._send({
                "kind": "result", "id": request_id, "ok": False,
                "error": str(exc), "error_kind": _error_kind(exc),
            })

    # -- heartbeats --------------------------------------------------------
    def _heartbeat_once(self) -> None:
        runtime = self._batcher.runtime
        self._send({
            "kind": "heartbeat",
            "worker": self._worker_id,
            "pid": os.getpid(),
            # Host identity block (telemetry/exporter.py): which machine
            # and process this heartbeat speaks for — the parent and the
            # fleet aggregator label merged metrics with it.
            "host": telemetry_mod.host_identity(),
            "queue_depth": self._batcher.queue_depth,
            "model_version": getattr(runtime, "model_version", 1),
            "degraded": getattr(runtime, "degraded", False),
            "ready": getattr(runtime, "ready", False),
            "metrics": telemetry_mod.current().metrics.transport_snapshot(),
        })

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self._heartbeat_interval_s):
            self._heartbeat_once()

    # -- swap protocol -----------------------------------------------------
    def _do_prepare(
        self, manifest: dict, runtime_config, carry_hot: bool = False
    ) -> None:
        version = int(manifest.get("version", 0))
        try:
            model, attachment = shm_model.attach_model(manifest)
            if carry_hot:
                # Delta apply: clone the SERVING runtime's compiled
                # kernels and hot sets around the attached model
                # (ScoringRuntime.patched) — the staged runtime costs
                # row rebuilds, not a cold compile+warmup pass.
                runtime = ScoringRuntime.patched(
                    self._batcher.runtime, model, {},
                    runtime_config or self._runtime_config,
                )
            else:
                runtime = ScoringRuntime(
                    model, {}, runtime_config or self._runtime_config
                )
            runtime.model_version = version
            runtime.model_path = manifest.get("path")
            margins, _ = runtime.score_rows([runtime.probe_row()])
            if not np.isfinite(margins[0]):
                raise ValueError(
                    f"staged v{version} probe scored non-finite "
                    f"{margins[0]!r}"
                )
        except Exception as exc:  # noqa: BLE001 — verdict crosses the pipe
            self._send({
                "kind": "swap_failed", "version": version,
                "error": f"{type(exc).__name__}: {exc}",
            })
            return
        old = self._prepared.pop(version, None)
        if old is not None:
            old[1].close()
        self._prepared[version] = (runtime, attachment)
        self._send({"kind": "swap_ready", "version": version})

    def _handle_swap_prepare(self, msg: dict) -> None:
        if self._prepare_thread is not None:
            self._prepare_thread.join()
        self._prepare_thread = threading.Thread(
            target=self._do_prepare,
            args=(
                msg["manifest"], msg.get("runtime_config"),
                bool(msg.get("carry_hot")),
            ),
            name=f"worker-{self._worker_id}-swap-prepare",
            daemon=True,
        )
        self._prepare_thread.start()

    def _handle_swap_commit(self, msg: dict) -> None:
        version = int(msg["version"])
        tenant = msg.get("tenant")
        runtime, attachment = self._prepared.pop(version)
        if tenant is not None:
            # Tenant-scoped commit: route ONE tenant onto the prepared
            # runtime; the default serving runtime never moves.  The
            # displaced route fills the tenant's one-slot rollback
            # window; whatever that evicts is done serving and closes.
            evicted = self._tenant_prev.pop(tenant, None)
            self._tenant_prev[tenant] = self._tenant_routes.get(tenant)
            self._tenant_routes[tenant] = (runtime, attachment, version)
            self._batcher.set_tenant_route(tenant, runtime)
            if evicted is not None:
                evicted[1].close()
            self._send({"kind": "swap_done", "version": version})
            return
        if self._previous is not None:
            self._previous[1].close()
        self._previous = (
            self._batcher.runtime, self._attachment,
            getattr(self._batcher.runtime, "model_version", 1),
        )
        # Same commit point as in-process swaps: one GIL-atomic
        # attribute write; the next dispatch scores on the new model.
        self._batcher.runtime = runtime
        self._attachment = attachment
        self._send({"kind": "swap_done", "version": version})

    def _handle_swap_rollback(self, msg: dict) -> None:
        tenant = msg.get("tenant")
        if tenant is not None:
            self._rollback_tenant_route(tenant)
            return
        if self._previous is None:
            self._send({
                "kind": "swap_done",
                "version": getattr(self._batcher.runtime, "model_version", 1),
                "rolled_back": False,
            })
            return
        runtime, attachment, version = self._previous
        self._previous = None
        retired_attachment = self._attachment
        self._batcher.runtime = runtime
        self._attachment = attachment
        retired_attachment.close()
        self._send({
            "kind": "swap_done", "version": version, "rolled_back": True,
        })

    def _rollback_tenant_route(self, tenant: str) -> None:
        """Restore the route the tenant's last swap displaced — or clear
        it (back to the default route) when that swap was the tenant's
        first.  No retained window (this worker respawned after the
        commit and replayed the route directly) answers
        ``rolled_back: False`` so the parent converge-kills us onto the
        restored registry."""
        if tenant not in self._tenant_prev:
            self._send({
                "kind": "swap_done",
                "version": getattr(self._batcher.runtime, "model_version", 1),
                "rolled_back": False,
            })
            return
        previous = self._tenant_prev.pop(tenant)
        dropped = self._tenant_routes.pop(tenant, None)
        if previous is None:
            self._batcher.clear_tenant_route(tenant)
            version = getattr(self._batcher.runtime, "model_version", 1)
        else:
            self._tenant_routes[tenant] = previous
            self._batcher.set_tenant_route(tenant, previous[0])
            version = previous[2]
        if dropped is not None:
            dropped[1].close()
        self._send({
            "kind": "swap_done", "version": version, "rolled_back": True,
        })

    def _handle_swap_abort(self, msg: dict) -> None:
        staged = self._prepared.pop(int(msg["version"]), None)
        if staged is not None:
            staged[1].close()

    # -- main loop ---------------------------------------------------------
    def run(self) -> None:
        self._hb_thread = threading.Thread(
            target=self._heartbeat_loop,
            name=f"worker-{self._worker_id}-heartbeat",
            daemon=True,
        )
        self._hb_thread.start()
        self._send({
            "kind": "ready",
            "worker": self._worker_id,
            "pid": os.getpid(),
            "model_version": self._runtime.model_version,
        })
        clean = False
        try:
            while not self._stop.is_set():
                message = self._conn.recv()
                if message is None:
                    break  # parent closed; wind down without a bye
                kind = message.get("kind")
                if kind == "score":
                    self._handle_score(message)
                elif kind == "stats":
                    self._send({
                        "kind": "result", "id": message.get("id"),
                        "ok": True, "value": self._stats(),
                    })
                elif kind == "set_quota":
                    # Fleet quota lease landing on this worker's batcher
                    # (serving/fleet.py): process-mode admission runs
                    # HERE, so the lease must cross the wire to bite.
                    try:
                        self._batcher.set_tenant_quota(
                            message["tenant"],
                            message.get("rate_rps"),
                            message.get("burst"),
                        )
                        self._send({
                            "kind": "result", "id": message.get("id"),
                            "ok": True, "value": True,
                        })
                    except Exception as exc:  # noqa: BLE001 — report
                        self._send({
                            "kind": "result", "id": message.get("id"),
                            "ok": False, "error": str(exc),
                            "error_kind": "bad_request",
                        })
                elif kind == "swap_prepare":
                    self._handle_swap_prepare(message)
                elif kind == "swap_commit":
                    self._handle_swap_commit(message)
                elif kind == "swap_rollback":
                    self._handle_swap_rollback(message)
                elif kind == "swap_abort":
                    self._handle_swap_abort(message)
                elif kind == "shutdown":
                    clean = True
                    break
        except Exception:  # noqa: BLE001 — desynced stream = wind down
            pass
        finally:
            self._stop.set()
            if self._prepare_thread is not None:
                self._prepare_thread.join(timeout=5.0)
            if self._hb_thread is not None:
                self._hb_thread.join(timeout=5.0)
            # Graceful drain: everything already admitted dispatches;
            # raced rows fail with the transient stopped-batcher verdict
            # the parent resubmits.
            self._batcher.stop()
            if clean:
                try:
                    self._conn.send({"kind": "bye"})
                except Exception:  # noqa: BLE001 — parent may be gone
                    pass
            self._conn.close()
            for staged in self._prepared.values():
                staged[1].close()
            if self._previous is not None:
                self._previous[1].close()
            for route in self._tenant_routes.values():
                route[1].close()
            for prev in self._tenant_prev.values():
                if prev is not None:
                    prev[1].close()
            self._attachment.close()

    def _handle_score(self, message: dict) -> None:
        request_id = message.get("id")
        row = message["row"]
        # The frame's tenant id wins over a missing row field so rows
        # pickled by an older parser still land in the right partition.
        tenant = message.get("tenant")
        if tenant is not None and getattr(row, "tenant", None) is None:
            row.tenant = tenant
        if message.get("stages"):
            row.want_stages = True
        # Cross-process trace adoption: the parent's propagated context
        # rides the score frame; adopting it around submit makes the
        # submitting thread's context — and through _Pending.ctx the
        # dispatch thread's serving.batch span — parent to the PARENT
        # process's span, so the request stitches into one trace.
        trace = message.get("trace")
        ctx = (
            telemetry_mod.TraceContext.parse(trace)
            if isinstance(trace, str) else None
        )
        try:
            with telemetry_mod.current().adopt(ctx):
                future = self._batcher.submit(
                    row,
                    timeout_ms=message.get("timeout_ms"),
                    bypass_admission=bool(message.get("bypass")),
                )
        except Exception as exc:  # noqa: BLE001 — sync admission verdict
            self._send({
                "kind": "result", "id": request_id, "ok": False,
                "error": str(exc), "error_kind": _error_kind(exc),
            })
            return
        future.add_done_callback(partial(self._send_result, request_id))

    def _stats(self) -> dict:
        stats = self._batcher.stats()
        stats["worker"] = self._worker_id
        stats["pid"] = os.getpid()
        stats["tenant_versions"] = {
            tenant: route[2]
            for tenant, route in self._tenant_routes.items()
        }
        runtime = self._batcher.runtime
        if isinstance(runtime, ScoringRuntime):
            stats["runtime"] = runtime.stats()
        return stats


def worker_main(
    sock,
    manifest: dict,
    worker_id: int,
    runtime_config=None,
    batcher_config=None,
    heartbeat_interval_s: float = 0.25,
) -> None:
    """Spawn target (module-level so the spawn pickler can import it).

    Installs a private enabled telemetry hub (sink-less by default:
    metrics only — the parent's heartbeat merge is this process's event
    stream), attaches the shared model, and serves frames until
    shutdown/EOF.  With ``PHOTON_TRACE_DIR`` set in the environment the
    hub grows real trace sinks — ``trace-worker-<id>-<pid>.trace.json``
    (Chrome trace array) and ``.jsonl`` (record log) under that
    directory — so the worker's spans can be merged with the parent's
    into one stitched distributed trace (docs/telemetry.md).  Startup
    failures are reported as a ``fatal`` frame so the parent's spawn
    raises a pointed error instead of timing out.
    """
    _pin_platform()
    conn = FrameConn(sock)
    sinks: list = []
    trace_dir = os.environ.get("PHOTON_TRACE_DIR")
    if trace_dir:
        try:
            os.makedirs(trace_dir, exist_ok=True)
            base = os.path.join(
                trace_dir, f"trace-worker-{worker_id}-{os.getpid()}"
            )
            sinks = [
                telemetry_mod.ChromeTraceSink(base + ".trace.json"),
                telemetry_mod.JsonlSink(base + ".jsonl"),
            ]
        except OSError:
            sinks = []  # tracing must never block serving startup
    hub = telemetry_mod.Telemetry(
        enabled=True, sinks=sinks, run_name=f"serving-worker-{worker_id}"
    )
    telemetry_mod.set_current(hub)
    try:
        main = _WorkerMain(
            conn, manifest, worker_id,
            runtime_config, batcher_config, heartbeat_interval_s,
        )
    except BaseException as exc:  # noqa: BLE001 — verdict crosses the pipe
        try:
            conn.send({
                "kind": "fatal",
                "worker": worker_id,
                "error": f"{type(exc).__name__}: {exc}",
            })
        except Exception:  # noqa: BLE001
            pass
        conn.close()
        hub.close()
        raise SystemExit(1)
    try:
        main.run()
    finally:
        # Flush the trace sinks (a sink-less close is a no-op): the
        # parent merges the written trace-worker files after stop.
        hub.close()
