"""Length-prefixed request/response framing for serving worker IPC.

One frame = a 4-byte big-endian payload length + a 1-byte payload kind
+ the payload body.  Both ends of the parent↔worker socketpair speak
it (serving/procpool.py routes, serving/worker.py serves).  Sends are
serialized under a lock — the parent's request threads and the
swapper, and the worker's dispatch callbacks and heartbeat thread, all
write the same socket — so frames never interleave.  Each side has
exactly one reader thread, so receives need no lock.

The hot path rides the binary wire codec (serving/wire.py) instead of
pickle: a ``{"kind": "score", ...}`` submission encodes as a score IPC
frame and a successful ``{"kind": "result", ...}`` as a result IPC
frame — no pickling a Row per request, no unpickling a dict per
result.  Everything else (stats, swaps, quota leases, heartbeats,
error results) stays pickled; the payload kind byte tells the receiver
which decoder to run, so the two coexist on one stream and any message
the codec cannot express falls back to pickle transparently.

``recv`` returns ``None`` on a clean EOF (peer closed or died); a
partial frame at EOF raises :class:`ProtocolError` — the caller treats
both as "worker gone" and fails in-flight work with a transient error
the supervisor resubmits.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional

from photon_ml_tpu.serving import wire as wire_mod

__all__ = ["FrameConn", "ProtocolError", "MAX_FRAME_BYTES"]

_HEADER = struct.Struct(">I")

#: sanity ceiling, not a tuning knob — a scoring row or a manifest is
#: kilobytes; a length beyond this means a corrupt or desynced stream.
MAX_FRAME_BYTES = 256 << 20

#: payload kind byte: what follows the length header.
_PAYLOAD_PICKLE = 0
_PAYLOAD_SCORE = 1
_PAYLOAD_RESULT = 2

#: a success value with exactly these keys is wire-expressible; stats
#: dicts and quota acks keep their pickle shape.
_RESULT_KEYS = frozenset(("score", "mean", "latency_ms"))


class ProtocolError(RuntimeError):
    """The byte stream desynced (oversized length or truncated frame)."""


def _encode_payload(message: Any) -> bytes:
    """Binary-encode hot-path messages; pickle the rest.  Any encode
    failure (a row the codec can't express, a foreign dict shape)
    falls back to pickle — correctness never depends on the fast
    path."""
    if isinstance(message, dict):
        kind = message.get("kind")
        try:
            if (
                kind == "score"
                and isinstance(message.get("id"), int)
                # The stage-annotation opt-in flag has no wire column;
                # it rides the pickle fallback (it is off the hot path
                # by definition).
                and not message.get("stages")
            ):
                return bytes([_PAYLOAD_SCORE]) + wire_mod.encode_score_ipc(
                    message["id"],
                    message["row"],
                    tenant=message.get("tenant"),
                    timeout_ms=message.get("timeout_ms"),
                    bypass=bool(message.get("bypass")),
                    trace=message.get("trace"),
                )
            if (
                kind == "result"
                and message.get("ok") is True
                and isinstance(message.get("id"), int)
                and isinstance(message.get("value"), dict)
                and set(message["value"]) == _RESULT_KEYS
            ):
                return bytes([_PAYLOAD_RESULT]) + wire_mod.encode_result_ipc(
                    message["id"], message["value"],
                    trace=message.get("trace"),
                )
        except Exception:  # noqa: BLE001 — fall back to pickle
            pass
    return bytes([_PAYLOAD_PICKLE]) + pickle.dumps(
        message, protocol=pickle.HIGHEST_PROTOCOL
    )


def _decode_payload(payload: bytes) -> Any:
    if not payload:
        raise ProtocolError("empty frame payload")
    tag, body = payload[0], memoryview(payload)[1:]
    if tag == _PAYLOAD_PICKLE:
        return pickle.loads(body)
    try:
        if tag == _PAYLOAD_SCORE:
            return wire_mod.decode_score_ipc(body)
        if tag == _PAYLOAD_RESULT:
            return wire_mod.decode_result_ipc(body)
    except wire_mod.WireFormatError as exc:
        raise ProtocolError(f"corrupt wire payload: {exc}") from exc
    raise ProtocolError(f"unknown payload kind byte {tag}")


class FrameConn:
    """One framed, pickling connection over a connected socket."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._closed = False

    def fileno(self) -> int:
        return self._sock.fileno()

    def send(self, message: Any) -> None:
        payload = _encode_payload(message)
        if len(payload) > MAX_FRAME_BYTES:
            raise ProtocolError(
                f"refusing to send a {len(payload)}-byte frame "
                f"(cap {MAX_FRAME_BYTES})"
            )
        frame = _HEADER.pack(len(payload)) + payload
        with self._send_lock:
            self._sock.sendall(frame)

    def _recv_exact(self, n: int) -> Optional[bytes]:
        chunks = []
        remaining = n
        while remaining:
            chunk = self._sock.recv(min(remaining, 1 << 20))
            if not chunk:
                if not chunks:
                    return None  # clean EOF between frames
                raise ProtocolError(
                    f"truncated frame: EOF with {remaining} of {n} "
                    "bytes unread"
                )
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def recv(self) -> Optional[Any]:
        """Next message, or ``None`` on clean EOF."""
        header = self._recv_exact(_HEADER.size)
        if header is None:
            return None
        (length,) = _HEADER.unpack(header)
        if length > MAX_FRAME_BYTES:
            raise ProtocolError(
                f"frame length {length} exceeds cap {MAX_FRAME_BYTES}; "
                "stream is desynced"
            )
        payload = self._recv_exact(length)
        if payload is None:
            raise ProtocolError("truncated frame: EOF before payload")
        return _decode_payload(payload)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
