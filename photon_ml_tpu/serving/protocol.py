"""Length-prefixed request/response framing for serving worker IPC.

One frame = a 4-byte big-endian payload length + a pickled message
dict.  Both ends of the parent↔worker socketpair speak it
(serving/procpool.py routes, serving/worker.py serves).  Sends are
serialized under a lock — the parent's request threads and the
swapper, and the worker's dispatch callbacks and heartbeat thread, all
write the same socket — so frames never interleave.  Each side has
exactly one reader thread, so receives need no lock.

``recv`` returns ``None`` on a clean EOF (peer closed or died); a
partial frame at EOF raises :class:`ProtocolError` — the caller treats
both as "worker gone" and fails in-flight work with a transient error
the supervisor resubmits.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional

__all__ = ["FrameConn", "ProtocolError", "MAX_FRAME_BYTES"]

_HEADER = struct.Struct(">I")

#: sanity ceiling, not a tuning knob — a scoring row or a manifest is
#: kilobytes; a length beyond this means a corrupt or desynced stream.
MAX_FRAME_BYTES = 256 << 20


class ProtocolError(RuntimeError):
    """The byte stream desynced (oversized length or truncated frame)."""


class FrameConn:
    """One framed, pickling connection over a connected socket."""

    def __init__(self, sock: socket.socket):
        self._sock = sock
        self._send_lock = threading.Lock()
        self._closed = False

    def fileno(self) -> int:
        return self._sock.fileno()

    def send(self, message: Any) -> None:
        payload = pickle.dumps(message, protocol=pickle.HIGHEST_PROTOCOL)
        if len(payload) > MAX_FRAME_BYTES:
            raise ProtocolError(
                f"refusing to send a {len(payload)}-byte frame "
                f"(cap {MAX_FRAME_BYTES})"
            )
        frame = _HEADER.pack(len(payload)) + payload
        with self._send_lock:
            self._sock.sendall(frame)

    def _recv_exact(self, n: int) -> Optional[bytes]:
        chunks = []
        remaining = n
        while remaining:
            chunk = self._sock.recv(min(remaining, 1 << 20))
            if not chunk:
                if not chunks:
                    return None  # clean EOF between frames
                raise ProtocolError(
                    f"truncated frame: EOF with {remaining} of {n} "
                    "bytes unread"
                )
            chunks.append(chunk)
            remaining -= len(chunk)
        return b"".join(chunks)

    def recv(self) -> Optional[Any]:
        """Next message, or ``None`` on clean EOF."""
        header = self._recv_exact(_HEADER.size)
        if header is None:
            return None
        (length,) = _HEADER.unpack(header)
        if length > MAX_FRAME_BYTES:
            raise ProtocolError(
                f"frame length {length} exceeds cap {MAX_FRAME_BYTES}; "
                "stream is desynced"
            )
        payload = self._recv_exact(length)
        if payload is None:
            raise ProtocolError("truncated frame: EOF before payload")
        return pickle.loads(payload)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self._sock.close()
