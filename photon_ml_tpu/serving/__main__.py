"""Serving CLI: selfcheck, HTTP server, and the built-in load generator.

Selfcheck (device-free beyond the CPU backend, CI-greppable)::

    python -m photon_ml_tpu.serving --selfcheck

builds a synthetic GAME model, warms the bucket ladder, serves CONCURRENT
requests through the real HTTP endpoint, and verifies:

- every batched score is BIT-IDENTICAL to single-request scoring
  (the padded-bucket kernel's parity contract);
- the telemetry snapshot carries request-latency histograms and a
  nonzero batch-occupancy gauge;
- /healthz and /stats answer.

Serve a saved model::

    python -m photon_ml_tpu.serving --model-dir /tmp/game_out --port 8080

Load-generate against an in-process service (no HTTP overhead)::

    python -m photon_ml_tpu.serving --synthetic 50000 \
        --loadgen closed --clients 16 --duration 5
    python -m photon_ml_tpu.serving --synthetic 50000 \
        --loadgen open --rate 500 --duration 5
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import urllib.request


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.serving",
        description="online GAME/GLM scoring service",
    )
    p.add_argument("--selfcheck", action="store_true")
    p.add_argument(
        "--model-dir",
        help="saved GAME model directory (or a GLM .avro file)",
    )
    p.add_argument(
        "--synthetic", type=int, metavar="N_ENTITIES", default=0,
        help="serve a synthetic GAME model with this many random-effect "
        "entities instead of --model-dir",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--max-batch-size", type=int, default=64)
    p.add_argument(
        "--max-wait-us", type=int, default=2000,
        help="how long the dispatcher holds the first request open for "
        "coalescing (docs/serving.md has the tuning guide)",
    )
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument(
        "--hot-entities", type=int, default=1024,
        help="per-coordinate LRU hot-set capacity (device-resident rows)",
    )
    p.add_argument(
        "--timeout-ms", type=float, default=None,
        help="default per-request deadline (None = no deadline)",
    )
    p.add_argument(
        "--loadgen", choices=["closed", "open"],
        help="run the built-in load generator against the service, print "
        "a JSON report, and exit",
    )
    p.add_argument("--clients", type=int, default=8, help="closed-loop")
    p.add_argument("--rate", type=float, default=200.0, help="open-loop rps")
    p.add_argument("--duration", type=float, default=5.0, help="seconds")
    p.add_argument(
        "--output-dir",
        help="telemetry output dir (selfcheck defaults to a tempdir)",
    )
    p.add_argument("--telemetry", choices=["on", "off"], default="on")
    p.add_argument(
        "--metrics-port", type=int, default=None,
        help="expose the live ops plane on this port (/metrics "
        "Prometheus exposition, /snapshot JSON, /healthz); 0 binds an "
        "ephemeral port; omit to disable",
    )
    p.add_argument(
        "--metrics-interval-s", type=float, default=1.0,
        help="metrics_ts.jsonl sampling interval when --output-dir is "
        "set (0 disables the time series)",
    )
    return p


def _make_service(args):
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService

    rt_cfg = RuntimeConfig(
        max_batch_size=args.max_batch_size, hot_entities=args.hot_entities
    )
    if args.synthetic:
        from photon_ml_tpu.serving.synthetic import SyntheticWorkload

        workload = SyntheticWorkload(n_entities=args.synthetic)
        runtime = ScoringRuntime(
            workload.model, workload.index_maps, rt_cfg
        )
    elif args.model_dir:
        workload = None
        runtime = ScoringRuntime.load(args.model_dir, rt_cfg)
    else:
        raise SystemExit(
            "one of --selfcheck / --model-dir / --synthetic is required"
        )
    service = ScoringService(runtime, BatcherConfig(
        max_batch_size=args.max_batch_size,
        max_wait_us=args.max_wait_us,
        max_queue=args.max_queue,
        default_timeout_ms=args.timeout_ms,
    ))
    return service, workload


# ---------------------------------------------------------------------------
# Selfcheck
# ---------------------------------------------------------------------------

def run_selfcheck(out_dir: str) -> list[str]:
    """Returns failure strings (empty = pass)."""
    import numpy as np

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService, start_http_server
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    failures: list[str] = []
    n_requests = 24
    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="serving-selfcheck"
    ) as tel:
        with tel.span("selfcheck", subsystem="serving"):
            # Small hot set (< entities) so BOTH the device hot-table path
            # and the host cold-gather path serve real traffic.
            workload = SyntheticWorkload(n_entities=64, seed=3)
            runtime = ScoringRuntime(
                workload.model, workload.index_maps,
                RuntimeConfig(max_batch_size=8, hot_entities=16),
            )
            requests = [workload.request(i) for i in range(n_requests)]
            rows = [runtime.parse_request(r) for r in requests]

            # Single-request reference: every row alone through bucket 1.
            reference = np.asarray(
                [runtime.score_rows([row])[0][0] for row in rows],
                np.float32,
            )

            service = ScoringService(runtime, BatcherConfig(
                max_batch_size=8, max_wait_us=20_000, max_queue=64,
            ))
            with service:
                server, _ = start_http_server(service, port=0)
                port = server.server_address[1]
                try:
                    # Concurrent clients through the REAL HTTP endpoint,
                    # 6 rows per POST, 4 posts in flight.
                    got: dict[int, list] = {}
                    errs: list[str] = []

                    def client(t: int) -> None:
                        chunk = requests[t * 6:(t + 1) * 6]
                        body = json.dumps({"rows": chunk}).encode()
                        req = urllib.request.Request(
                            f"http://127.0.0.1:{port}/score",
                            data=body,
                            headers={"Content-Type": "application/json"},
                        )
                        try:
                            with urllib.request.urlopen(
                                req, timeout=30
                            ) as resp:
                                got[t] = json.loads(resp.read())["results"]
                        except Exception as exc:  # noqa: BLE001
                            errs.append(f"client {t}: {exc}")

                    threads = [
                        threading.Thread(target=client, args=(t,))
                        for t in range(4)
                    ]
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                    failures.extend(errs)

                    served = np.zeros(n_requests, np.float32)
                    for t, results in got.items():
                        for j, r in enumerate(results):
                            if "error" in r:
                                failures.append(
                                    f"row {t * 6 + j} failed: {r}"
                                )
                            else:
                                served[t * 6 + j] = np.float32(r["score"])
                    if not failures and served.tobytes() != \
                            reference.tobytes():
                        bad = int(np.argmax(served != reference))
                        failures.append(
                            "batched scores are NOT bit-identical to "
                            f"single-request scoring (first diff row "
                            f"{bad}: {served[bad]!r} vs "
                            f"{reference[bad]!r})"
                        )

                    # /healthz and /stats answer.
                    for route in ("/healthz", "/stats"):
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}{route}", timeout=10
                        ) as resp:
                            if resp.status != 200:
                                failures.append(
                                    f"{route} -> HTTP {resp.status}"
                                )
                            json.loads(resp.read())
                finally:
                    server.shutdown()
                    server.server_close()

        snap = tel.snapshot()
    # Snapshot content: request-latency histogram + nonzero occupancy.
    hist = snap["histograms"].get("serving_request_latency_seconds", {})
    if not hist.get("count"):
        failures.append(
            "metrics snapshot has no serving_request_latency_seconds "
            "histogram observations"
        )
    occupancy = snap["gauges"].get("serving_batch_occupancy")
    if not occupancy:
        failures.append(
            f"serving_batch_occupancy gauge is {occupancy!r}, expected "
            "nonzero"
        )
    metrics_path = os.path.join(out_dir, "metrics.json")
    if not os.path.exists(metrics_path):
        failures.append(f"missing {metrics_path}")
    else:
        with open(metrics_path) as f:
            on_disk = json.load(f)
        if "serving_request_latency_seconds" not in on_disk.get(
            "histograms", {}
        ):
            failures.append(
                "metrics.json lacks the request-latency histogram"
            )
    if not failures:
        hot = runtime.stats()["hot_sets"]["per_entity"]
        print(
            f"serving selfcheck: {n_requests} rows bit-identical over "
            f"{runtime.batches - n_requests} coalesced batches "
            f"(buckets {runtime.buckets}, hot hits {hot['hits']}, cold "
            f"misses {hot['misses']}, mean latency "
            f"{1e3 * hist['sum'] / hist['count']:.2f} ms), "
            f"occupancy gauge {occupancy:.3f}"
        )
    return failures


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)

    if args.selfcheck:
        if args.output_dir:
            os.makedirs(args.output_dir, exist_ok=True)
            failures = run_selfcheck(args.output_dir)
        else:
            with tempfile.TemporaryDirectory(
                prefix="photon_serving_selfcheck_"
            ) as td:
                failures = run_selfcheck(td)
        if failures:
            print("serving selfcheck FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
            return 1
        print("serving selfcheck PASSED")
        return 0

    from photon_ml_tpu import telemetry as telemetry_mod

    tel = telemetry_mod.Telemetry(
        output_dir=args.output_dir,
        enabled=args.telemetry != "off",
        run_name="serving",
        sinks=None if args.output_dir else [],
    )
    with tel, telemetry_mod.mount_ops_plane(
        tel, port=args.metrics_port, interval_s=args.metrics_interval_s
    ) as plane:
        if plane.port is not None:
            print(
                f"metrics on http://127.0.0.1:{plane.port} "
                "(/metrics /snapshot /healthz)",
                flush=True,
            )
        service, workload = _make_service(args)
        if args.loadgen:
            from photon_ml_tpu.serving import loadgen

            if workload is None:
                from photon_ml_tpu.serving.synthetic import SyntheticWorkload

                workload = SyntheticWorkload(n_entities=10_000)
            with service:
                if args.loadgen == "closed":
                    report = loadgen.closed_loop(
                        service.submit, workload.request,
                        clients=args.clients, duration_s=args.duration,
                    )
                else:
                    report = loadgen.open_loop(
                        service.submit, workload.request,
                        rate_rps=args.rate, duration_s=args.duration,
                    )
            print(json.dumps({
                "loadgen": report.snapshot(),
                "stats": service.stats(),
            }, indent=2))
            return 0

        from photon_ml_tpu.serving.service import start_http_server

        with service:
            server, thread = start_http_server(
                service, host=args.host, port=args.port
            )
            host, port = server.server_address[:2]
            print(
                f"serving on http://{host}:{port} "
                f"(/score /healthz /stats); Ctrl-C to stop",
                flush=True,
            )
            try:
                thread.join()
            except KeyboardInterrupt:
                print("shutting down")
            finally:
                server.shutdown()
                server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
