"""Serving CLI: selfcheck, HTTP server, and the built-in load generator.

Selfcheck (device-free beyond the CPU backend, CI-greppable)::

    python -m photon_ml_tpu.serving --selfcheck

builds a synthetic GAME model, warms the bucket ladder, serves CONCURRENT
requests through the real HTTP endpoint, and verifies:

- every batched score is BIT-IDENTICAL to single-request scoring
  (the padded-bucket kernel's parity contract);
- the telemetry snapshot carries request-latency histograms and a
  nonzero batch-occupancy gauge;
- /healthz and /stats answer.

A second, high-availability pass then runs a 2-replica supervisor under
open-loop load while (a) one replica is killed and restarts and (b) the
model is hot-swapped v1 -> v2 over HTTP ``/reload`` and a TAMPERED model
directory is rejected with an automatic rollback — asserting ZERO failed
requests throughout and a monotone ``serving_model_version`` in
metrics.json.

A third, tenancy pass replays the ``noisy_neighbor`` scenario against
a two-tenant policy: an aggressor tenant bursting to ~10x its
token-bucket quota is shed alone while the victim tenant's p99 stays
inside its SLO with zero failures, and the per-tenant
``serving_tenant_<t>_*`` metric family records both sides.

A fourth, fleet pass runs whole HOSTS behind a ``FleetRouter`` with a
``QuotaCoordinator`` leasing each tenant's fleet budget across hosts
(serving/fleet.py): a host kill under >= 120 rps costs zero failed
requests and zero rejections for the in-quota tenant, and a scripted
coordinator partition holds fleet-wide admission within one lease
window of the budget (degrade-to-last-lease), recovering to exact
enforcement after heal.

``--tenant-report metrics_ts.jsonl`` prints per-tenant accounting
(rps, shed, latency percentiles) from the ``serving_tenant_*`` family
of a recorded time series and exits.

Process mode (``--selfcheck --workers 2``) runs the same contracts
against CRASH-ISOLATED worker processes attached to one shared-memory
model publication: score parity with in-process scoring, a real SIGKILL
mid-load with zero failed requests, a cross-process hot swap + rollback
(bit-identical on both sides), a ``serving_shared_segment_bytes`` gauge
at one publication (not N copies), and a leak-free shutdown under a
strict :class:`ProcessLeakSentinel` with no shared segments left
mapped — then the same noisy-neighbor tenancy pass with the tenant id
riding the worker wire protocol.

Serve a saved model::

    python -m photon_ml_tpu.serving --model-dir /tmp/game_out --port 8080

Load-generate against an in-process service (no HTTP overhead)::

    python -m photon_ml_tpu.serving --synthetic 50000 \
        --loadgen closed --clients 16 --duration 5
    python -m photon_ml_tpu.serving --synthetic 50000 \
        --loadgen open --rate 500 --duration 5
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import urllib.error
import urllib.request


def build_arg_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m photon_ml_tpu.serving",
        description="online GAME/GLM scoring service",
    )
    p.add_argument("--selfcheck", action="store_true")
    p.add_argument(
        "--model-dir",
        help="saved GAME model directory (or a GLM .avro file)",
    )
    p.add_argument(
        "--synthetic", type=int, metavar="N_ENTITIES", default=0,
        help="serve a synthetic GAME model with this many random-effect "
        "entities instead of --model-dir",
    )
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8080)
    p.add_argument("--max-batch-size", type=int, default=64)
    p.add_argument(
        "--max-wait-us", type=int, default=2000,
        help="how long the dispatcher holds the first request open for "
        "coalescing (docs/serving.md has the tuning guide)",
    )
    p.add_argument("--max-queue", type=int, default=256)
    p.add_argument(
        "--adaptive-wait", action="store_true",
        help="size the coalescing wait from the arrival-rate EWMA "
        "instead of always paying --max-wait-us (which becomes the "
        "ceiling); docs/serving.md#data-plane",
    )
    p.add_argument(
        "--shm-ingress", metavar="NAME", nargs="?", const="", default=None,
        help="also serve same-machine clients over a shared-memory "
        "ingress ring (skips HTTP entirely); optional segment NAME, "
        "auto-generated when omitted",
    )
    p.add_argument(
        "--hot-entities", type=int, default=1024,
        help="per-coordinate LRU hot-set capacity (device-resident rows)",
    )
    p.add_argument(
        "--replicas", type=int, default=1,
        help="run this many supervised scoring replicas behind the "
        "listener (>1 enables the HA path: health probes, automatic "
        "restarts, request resubmission; docs/serving.md)",
    )
    p.add_argument(
        "--workers", type=int, default=0,
        help="score in this many crash-isolated worker PROCESSES "
        "attached to one shared-memory model publication instead of "
        "in-process replica threads (docs/serving.md#process-mode); "
        "with --selfcheck, runs the process-mode pass instead of the "
        "in-process passes",
    )
    p.add_argument(
        "--timeout-ms", type=float, default=None,
        help="default per-request deadline (None = no deadline)",
    )
    p.add_argument(
        "--tenant-report", metavar="METRICS_TS_JSONL", nargs="+",
        help="summarize per-tenant rps/shed/p99 from one or more "
        "metrics_ts.jsonl files (the serving_tenant_* family) as JSON "
        "and exit; several files — one per host — merge into per-host "
        "sections plus a fleet-wide fold",
    )
    p.add_argument(
        "--loadgen", choices=["closed", "open"],
        help="run the built-in load generator against the service, print "
        "a JSON report, and exit",
    )
    p.add_argument("--clients", type=int, default=8, help="closed-loop")
    p.add_argument("--rate", type=float, default=200.0, help="open-loop rps")
    p.add_argument("--duration", type=float, default=5.0, help="seconds")
    p.add_argument(
        "--output-dir",
        help="telemetry output dir (selfcheck defaults to a tempdir)",
    )
    p.add_argument("--telemetry", choices=["on", "off"], default="on")
    p.add_argument(
        "--metrics-port", type=int, default=None,
        help="expose the live ops plane on this port (/metrics "
        "Prometheus exposition, /snapshot JSON, /healthz); 0 binds an "
        "ephemeral port; omit to disable",
    )
    p.add_argument(
        "--metrics-interval-s", type=float, default=1.0,
        help="metrics_ts.jsonl sampling interval when --output-dir is "
        "set (0 disables the time series)",
    )
    p.add_argument(
        "--membership", metavar="REGISTRY_URL", default=None,
        help="register this server with a cluster membership registry "
        "(photon_ml_tpu.cluster) and heartbeat for the lifetime of the "
        "process; drained and removed on shutdown",
    )
    p.add_argument(
        "--host-id", default=None,
        help="membership host id (default: host:port of the listener)",
    )
    p.add_argument(
        "--fleet-join", metavar="SERVING_URL", default=None,
        help="one-shot admin verb: register SERVING_URL with the "
        "--membership registry and exit; the MembershipWatcher joins "
        "it into the live rotation (ops/README.md runbook)",
    )
    p.add_argument(
        "--fleet-drain", metavar="HOST_ID", default=None,
        help="one-shot admin verb: mark HOST_ID draining in the "
        "--membership registry and exit; the watcher drains it from "
        "the router once converged",
    )
    return p


def _make_service(args):
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService

    rt_cfg = RuntimeConfig(
        max_batch_size=args.max_batch_size, hot_entities=args.hot_entities
    )
    if args.synthetic:
        from photon_ml_tpu.serving.synthetic import SyntheticWorkload

        workload = SyntheticWorkload(n_entities=args.synthetic)

        def factory() -> ScoringRuntime:
            return ScoringRuntime(
                workload.model, workload.index_maps, rt_cfg
            )
    elif args.model_dir:
        workload = None

        def factory() -> ScoringRuntime:
            return ScoringRuntime.load(args.model_dir, rt_cfg)
    else:
        raise SystemExit(
            "one of --selfcheck / --model-dir / --synthetic is required"
        )
    batcher_cfg = BatcherConfig(
        max_batch_size=args.max_batch_size,
        max_wait_us=args.max_wait_us,
        max_queue=args.max_queue,
        default_timeout_ms=args.timeout_ms,
        adaptive_wait=args.adaptive_wait,
    )
    if args.workers:
        from photon_ml_tpu.serving.procpool import WorkerPool
        from photon_ml_tpu.serving.supervisor import ReplicaSupervisor

        if workload is not None:
            model, index_maps, path = (
                workload.model, workload.index_maps, None
            )
        else:
            from photon_ml_tpu.io.game_store import load_game_model

            model, index_maps = load_game_model(args.model_dir)
            path = args.model_dir
        pool = WorkerPool(
            model, index_maps, runtime_config=rt_cfg, model_path=path
        )
        unit = ReplicaSupervisor(pool=pool, n_replicas=args.workers)
    elif args.replicas > 1:
        from photon_ml_tpu.serving.supervisor import ReplicaSupervisor

        unit = ReplicaSupervisor(factory, n_replicas=args.replicas)
    else:
        unit = factory()
    service = ScoringService(unit, batcher_cfg)
    return service, workload


# ---------------------------------------------------------------------------
# Selfcheck
# ---------------------------------------------------------------------------

def run_selfcheck(out_dir: str) -> list[str]:
    """Returns failure strings (empty = pass)."""
    import numpy as np

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService, start_http_server
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    failures: list[str] = []
    n_requests = 24
    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="serving-selfcheck"
    ) as tel:
        with tel.span("selfcheck", subsystem="serving"):
            # Small hot set (< entities) so BOTH the device hot-table path
            # and the host cold-gather path serve real traffic.
            workload = SyntheticWorkload(n_entities=64, seed=3)
            runtime = ScoringRuntime(
                workload.model, workload.index_maps,
                RuntimeConfig(max_batch_size=8, hot_entities=16),
            )
            requests = [workload.request(i) for i in range(n_requests)]
            rows = [runtime.parse_request(r) for r in requests]

            # Single-request reference: every row alone through bucket 1.
            reference = np.asarray(
                [runtime.score_rows([row])[0][0] for row in rows],
                np.float32,
            )

            service = ScoringService(runtime, BatcherConfig(
                max_batch_size=8, max_wait_us=20_000, max_queue=64,
            ))
            with service:
                server, _ = start_http_server(service, port=0)
                port = server.server_address[1]
                try:
                    # Concurrent clients through the REAL HTTP endpoint,
                    # 6 rows per POST, 4 posts in flight.
                    got: dict[int, list] = {}
                    errs: list[str] = []

                    def client(t: int) -> None:
                        chunk = requests[t * 6:(t + 1) * 6]
                        body = json.dumps({"rows": chunk}).encode()
                        req = urllib.request.Request(
                            f"http://127.0.0.1:{port}/score",
                            data=body,
                            headers={"Content-Type": "application/json"},
                        )
                        try:
                            with urllib.request.urlopen(
                                req, timeout=30
                            ) as resp:
                                got[t] = json.loads(resp.read())["results"]
                        except Exception as exc:  # noqa: BLE001
                            errs.append(f"client {t}: {exc}")

                    threads = [
                        threading.Thread(
                            target=client, args=(t,), daemon=True
                        )
                        for t in range(4)
                    ]
                    try:
                        for t in threads:
                            t.start()
                    finally:
                        for t in threads:
                            t.join()
                    failures.extend(errs)

                    served = np.zeros(n_requests, np.float32)
                    for t, results in got.items():
                        for j, r in enumerate(results):
                            if "error" in r:
                                failures.append(
                                    f"row {t * 6 + j} failed: {r}"
                                )
                            else:
                                served[t * 6 + j] = np.float32(r["score"])
                    if not failures and served.tobytes() != \
                            reference.tobytes():
                        bad = int(np.argmax(served != reference))
                        failures.append(
                            "batched scores are NOT bit-identical to "
                            f"single-request scoring (first diff row "
                            f"{bad}: {served[bad]!r} vs "
                            f"{reference[bad]!r})"
                        )

                    # /healthz and /stats answer.
                    for route in ("/healthz", "/stats"):
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{port}{route}", timeout=10
                        ) as resp:
                            if resp.status != 200:
                                failures.append(
                                    f"{route} -> HTTP {resp.status}"
                                )
                            json.loads(resp.read())
                finally:
                    server.shutdown()
                    server.server_close()

        snap = tel.snapshot()
    # Snapshot content: request-latency histogram + nonzero occupancy.
    hist = snap["histograms"].get("serving_request_latency_seconds", {})
    if not hist.get("count"):
        failures.append(
            "metrics snapshot has no serving_request_latency_seconds "
            "histogram observations"
        )
    occupancy = snap["gauges"].get("serving_batch_occupancy")
    if not occupancy:
        failures.append(
            f"serving_batch_occupancy gauge is {occupancy!r}, expected "
            "nonzero"
        )
    metrics_path = os.path.join(out_dir, "metrics.json")
    if not os.path.exists(metrics_path):
        failures.append(f"missing {metrics_path}")
    else:
        with open(metrics_path) as f:
            on_disk = json.load(f)
        if "serving_request_latency_seconds" not in on_disk.get(
            "histograms", {}
        ):
            failures.append(
                "metrics.json lacks the request-latency histogram"
            )
    if not failures:
        hot = runtime.stats()["hot_sets"]["per_entity"]
        print(
            f"serving selfcheck: {n_requests} rows bit-identical over "
            f"{runtime.batches - n_requests} coalesced batches "
            f"(buckets {runtime.buckets}, hot hits {hot['hits']}, cold "
            f"misses {hot['misses']}, mean latency "
            f"{1e3 * hist['sum'] / hist['count']:.2f} ms), "
            f"occupancy gauge {occupancy:.3f}"
        )
    return failures


def run_selfcheck_ha(out_dir: str) -> list[str]:
    """High-availability pass: replica kill + hot-swap + tampered-model
    rollback under open-loop load, zero failed requests.  Returns
    failure strings (empty = pass)."""
    import shutil
    import time

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.io.game_store import save_game_model
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService, start_http_server
    from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    failures: list[str] = []
    # Two model versions with identical shard shapes (so the same request
    # stream scores on both), one tampered copy of v2.
    v1 = SyntheticWorkload(n_entities=64, seed=3)
    v2 = SyntheticWorkload(n_entities=64, seed=4)
    models_dir = os.path.join(out_dir, "models")
    v1_dir = os.path.join(models_dir, "v1")
    v2_dir = os.path.join(models_dir, "v2")
    bad_dir = os.path.join(models_dir, "v2-tampered")
    save_game_model(v1.model, v1.index_maps, v1_dir)
    save_game_model(v2.model, v2.index_maps, v2_dir)
    shutil.copytree(v2_dir, bad_dir)
    bad_avro = os.path.join(
        bad_dir, "random-effect", "per_entity", "coefficients.avro"
    )
    with open(bad_avro, "r+b") as f:
        f.seek(-64, os.SEEK_END)
        byte = f.read(1)
        f.seek(-64, os.SEEK_END)
        f.write(bytes([byte[0] ^ 0xFF]))

    rt_cfg = RuntimeConfig(max_batch_size=8, hot_entities=16)

    def factory() -> ScoringRuntime:
        return ScoringRuntime.load(v1_dir, rt_cfg)

    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="serving-selfcheck-ha"
    ) as tel:
        supervisor = ReplicaSupervisor(
            factory, n_replicas=2, probe_interval_s=0.1
        )
        service = ScoringService(supervisor, BatcherConfig(
            max_batch_size=8, max_wait_us=2_000, max_queue=256,
        ))
        versions: list[int] = []
        with service:
            server, _ = start_http_server(service, port=0)
            port = server.server_address[1]
            base = f"http://127.0.0.1:{port}"
            try:
                def http(method: str, route: str, body=None):
                    req = urllib.request.Request(
                        base + route,
                        method=method,
                        data=None if body is None else
                        json.dumps(body).encode(),
                        headers={"Content-Type": "application/json"},
                    )
                    try:
                        with urllib.request.urlopen(req, timeout=30) as r:
                            return r.status, json.loads(r.read())
                    except urllib.error.HTTPError as e:
                        return e.code, json.loads(e.read())

                def script() -> None:
                    # Fires while the open loop below is running.
                    try:
                        time.sleep(0.4)
                        versions.append(service.swapper.version)
                        # A burst straight into the queues right before
                        # the kill guarantees in-flight work on the dying
                        # replica — the resubmission path, not just the
                        # routing-exclusion path, must be exercised.
                        burst = [
                            service.submit(v1.request(50_000 + j))
                            for j in range(64)
                        ]
                        supervisor.kill_replica(0)
                        for bf in burst:
                            try:
                                bf.result(timeout=30)
                            except Exception as exc:  # noqa: BLE001
                                failures.append(
                                    "burst request failed after replica "
                                    f"kill: {exc!r}"
                                )
                                break
                        deadline = time.monotonic() + 10
                        while (
                            supervisor.healthy_count < 2
                            and time.monotonic() < deadline
                        ):
                            time.sleep(0.05)
                        if supervisor.healthy_count < 2:
                            failures.append(
                                "killed replica did not restart within "
                                "10 s"
                            )
                        status, swapped = http(
                            "POST", "/reload", {"model_dir": v2_dir}
                        )
                        if status != 200 or swapped["status"] != "swapped":
                            failures.append(
                                f"/reload v2 -> HTTP {status} {swapped}"
                            )
                        versions.append(service.swapper.version)
                        status, rolled = http(
                            "POST", "/reload", {"model_dir": bad_dir}
                        )
                        if status != 422 or \
                                rolled["status"] != "rolled_back":
                            failures.append(
                                "/reload tampered dir -> HTTP "
                                f"{status} {rolled} (expected 422 "
                                "rolled_back)"
                            )
                        versions.append(service.swapper.version)
                    except Exception as exc:  # noqa: BLE001
                        failures.append(f"HA script failed: {exc!r}")

                script_thread = threading.Thread(
                    target=script, daemon=True
                )
                script_thread.start()
                report = loadgen.open_loop(
                    service.submit, v1.request,
                    rate_rps=120.0, duration_s=4.0,
                )
                script_thread.join(timeout=30)
                if report.errors or report.rejected:
                    failures.append(
                        f"HA load saw {report.errors} errors and "
                        f"{report.rejected} rejections (expected 0/0) "
                        f"across {report.completed} requests"
                    )
                if report.completed < 100:
                    failures.append(
                        f"HA load completed only {report.completed} "
                        "requests; the pass did not exercise the path"
                    )
                if versions != sorted(versions):
                    failures.append(
                        f"model_version went backwards: {versions}"
                    )
                if service.swapper.version != 2:
                    failures.append(
                        "expected model_version 2 after swap + rejected "
                        f"tamper, got {service.swapper.version}"
                    )
                for route, want in (("/livez", 200), ("/readyz", 200)):
                    status, _body = http("GET", route)
                    if status != want:
                        failures.append(
                            f"{route} -> HTTP {status}, expected {want}"
                        )
                status, health = http("GET", "/healthz")
                if health.get("status") != "ok":
                    failures.append(f"/healthz after HA pass: {health}")
            finally:
                server.shutdown()
                server.server_close()
        snap = tel.snapshot()

    counters = snap["counters"]
    gauges = snap["gauges"]
    for name, minimum in (
        ("serving_swaps_total", 1),
        ("serving_rollbacks_total", 1),
        ("serving_replica_restarts_total", 1),
        ("serving_resubmitted_total", 1),
    ):
        if counters.get(name, 0) < minimum:
            failures.append(
                f"{name} = {counters.get(name, 0)}, expected >= {minimum}"
            )
    metrics_path = os.path.join(out_dir, "metrics.json")
    if not os.path.exists(metrics_path):
        failures.append(f"missing {metrics_path}")
    else:
        with open(metrics_path) as f:
            on_disk = json.load(f)
        if on_disk.get("gauges", {}).get("serving_model_version") != 2:
            failures.append(
                "metrics.json serving_model_version = "
                f"{on_disk.get('gauges', {}).get('serving_model_version')!r}"
                ", expected 2"
            )
    if not failures:
        print(
            "serving HA selfcheck: replica kill + v1->v2 hot swap + "
            "tampered-model rollback under load, 0 failed requests "
            f"(restarts {counters.get('serving_replica_restarts_total')}, "
            f"resubmitted {counters.get('serving_resubmitted_total')}, "
            f"swaps {counters.get('serving_swaps_total')}, rollbacks "
            f"{counters.get('serving_rollbacks_total')}, final version "
            f"{gauges.get('serving_model_version')})"
        )
    return failures


def run_selfcheck_process(out_dir: str, n_workers: int = 2) -> list[str]:
    """Process-mode pass: crash-isolated worker processes on a shared
    model.  Verifies score parity with in-process scoring, zero failed
    requests through a real SIGKILL under open-loop load, a
    cross-process hot swap + rollback (bit-identical on both sides),
    single-publication segment accounting, and a leak-free shutdown.
    Returns failure strings (empty = pass)."""
    import time

    import numpy as np

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.analysis.sanitizers import ProcessLeakSentinel
    from photon_ml_tpu.io.game_store import save_game_model
    from photon_ml_tpu.serving import loadgen, shm_model
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.procpool import WorkerPool
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.supervisor import ReplicaSupervisor
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload

    failures: list[str] = []
    n_requests = 24
    v1 = SyntheticWorkload(n_entities=64, seed=3)
    v2 = SyntheticWorkload(n_entities=64, seed=4)
    v2_dir = os.path.join(out_dir, "models", "v2")
    save_game_model(v2.model, v2.index_maps, v2_dir)
    rt_cfg = RuntimeConfig(max_batch_size=8, hot_entities=16)
    requests = [v1.request(i) for i in range(n_requests)]

    def reference(w: SyntheticWorkload) -> np.ndarray:
        rt = ScoringRuntime(w.model, w.index_maps, rt_cfg)
        return np.asarray(
            [
                rt.score_rows([rt.parse_request(r)])[0][0]
                for r in requests
            ],
            np.float32,
        )

    ref_v1, ref_v2 = reference(v1), reference(v2)

    def parity(tag: str, want: np.ndarray) -> None:
        futs = [service.submit(r) for r in requests]
        got = np.asarray(
            [np.float32(f.result(timeout=60)["score"]) for f in futs],
            np.float32,
        )
        if got.tobytes() != want.tobytes():
            bad = int(np.argmax(got != want))
            failures.append(
                f"{tag}: worker scores are NOT bit-identical to "
                f"in-process scoring (first diff row {bad}: "
                f"{got[bad]!r} vs {want[bad]!r})"
            )

    def await_healthy(what: str, timeout_s: float = 30.0) -> None:
        deadline = time.monotonic() + timeout_s
        while (
            supervisor.healthy_count < n_workers
            and time.monotonic() < deadline
        ):
            time.sleep(0.05)
        if supervisor.healthy_count < n_workers:
            failures.append(
                f"{what}: only {supervisor.healthy_count}/{n_workers} "
                f"workers healthy after {timeout_s:.0f} s"
            )

    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="serving-selfcheck-proc"
    ) as tel:
        with ProcessLeakSentinel(grace_s=15.0, strict=True):
            pool = WorkerPool(
                v1.model, v1.index_maps, runtime_config=rt_cfg, version=1
            )
            supervisor = ReplicaSupervisor(
                pool=pool, n_replicas=n_workers, probe_interval_s=0.1
            )
            service = ScoringService(supervisor, BatcherConfig(
                max_batch_size=8, max_wait_us=2_000, max_queue=256,
            ))
            with service:
                parity("v1", ref_v1)

                # One publication, N attachments: the parent-side gauge
                # counts mapped bytes ONCE however many workers attach.
                published = sum(
                    seg["nbytes"]
                    for seg in pool.manifest["segments"].values()
                )
                mapped = tel.snapshot()["gauges"].get(
                    "serving_shared_segment_bytes", 0
                )
                if mapped != published:
                    failures.append(
                        "serving_shared_segment_bytes = "
                        f"{mapped}, expected exactly one publication "
                        f"({published} bytes) for {n_workers} workers"
                    )

                # Real SIGKILL mid-load: a burst straight into the dying
                # worker's queue plus an open loop across the kill, zero
                # failed requests end to end.
                def script() -> None:
                    try:
                        time.sleep(0.4)
                        burst = [
                            service.submit(v1.request(50_000 + j))
                            for j in range(64)
                        ]
                        supervisor.kill_replica(0)
                        for bf in burst:
                            try:
                                bf.result(timeout=60)
                            except Exception as exc:  # noqa: BLE001
                                failures.append(
                                    "burst request failed after worker "
                                    f"SIGKILL: {exc!r}"
                                )
                                break
                        await_healthy("post-SIGKILL respawn")
                    except Exception as exc:  # noqa: BLE001
                        failures.append(
                            f"process script failed: {exc!r}"
                        )

                script_thread = threading.Thread(
                    target=script, daemon=True
                )
                script_thread.start()
                report = loadgen.open_loop(
                    service.submit, v1.request,
                    rate_rps=120.0, duration_s=4.0,
                )
                script_thread.join(timeout=60)
                if report.errors or report.rejected:
                    failures.append(
                        f"process load saw {report.errors} errors and "
                        f"{report.rejected} rejections (expected 0/0) "
                        f"across {report.completed} requests"
                    )
                if report.completed < 100:
                    failures.append(
                        f"process load completed only {report.completed}"
                        " requests; the pass did not exercise the path"
                    )

                # Cross-process hot swap, then an operator rollback with
                # a worker killed in between (the respawned worker has
                # no retained previous; rollback must still converge).
                swapped = service.reload(v2_dir)
                if swapped.status != "swapped":
                    failures.append(f"process swap v2 -> {swapped}")
                parity("post-swap v2", ref_v2)
                if service.swapper.version != 2:
                    failures.append(
                        "expected model_version 2 after swap, got "
                        f"{service.swapper.version}"
                    )
                supervisor.kill_replica(1, "post-swap kill")
                await_healthy("post-swap respawn")
                rolled = service.swapper.rollback()
                if rolled.status != "rolled_back":
                    failures.append(f"process rollback -> {rolled}")
                await_healthy("rollback convergence")
                parity("post-rollback v1", ref_v1)
            leftover = shm_model.live_segments()
            if leftover:
                failures.append(
                    "shared segments still mapped after shutdown: "
                    f"{leftover}"
                )
        snap = tel.snapshot()

    counters = snap["counters"]
    for name, minimum in (
        ("serving_replica_restarts_total", 2),
        ("serving_resubmitted_total", 1),
        ("serving_swaps_total", 1),
        ("serving_rollbacks_total", 1),
    ):
        if counters.get(name, 0) < minimum:
            failures.append(
                f"{name} = {counters.get(name, 0)}, expected >= {minimum}"
            )
    if not failures:
        print(
            f"serving process selfcheck: {n_workers} worker processes, "
            f"{n_requests}-row parity x3 (v1, swapped v2, rolled-back "
            "v1) bit-identical, SIGKILL under 120 rps with 0 failed "
            "requests "
            f"({report.completed} completed, restarts "
            f"{counters.get('serving_replica_restarts_total')}, "
            f"resubmitted "
            f"{counters.get('serving_resubmitted_total')}), shared "
            f"segments {published} bytes mapped once, shutdown "
            "leak-free"
        )
    return failures


def run_selfcheck_tenancy(out_dir: str, n_workers: int = 0) -> list[str]:
    """Two-tenant noisy-neighbor pass: an aggressor tenant bursts to
    ~10x its quota while a victim tenant holds steady; the tenancy
    layer must shed the aggressor alone — victim p99 inside its SLO
    with ZERO failed requests — and the per-tenant metric family must
    record both sides.  ``n_workers=0`` runs in-process; >0 runs the
    same policy in crash-isolated worker processes (the TenancyConfig
    rides BatcherConfig into each spawned worker).  Returns failure
    strings (empty = pass)."""
    import time

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload
    from photon_ml_tpu.serving.tenancy import TenancyConfig, TenantSpec

    failures: list[str] = []
    victim_slo_ms = 500.0
    # Quotas are enforced per batcher (per worker): size the aggressor's
    # so its 10x burst is 10x the AGGREGATE admitted rate.
    aggressor_quota = 40.0 / max(n_workers, 1)
    workload = SyntheticWorkload(n_entities=64, seed=3)
    rt_cfg = RuntimeConfig(max_batch_size=8, hot_entities=16)
    tenancy = TenancyConfig(tenants=(
        TenantSpec(
            name="victim", max_queue=128, p99_slo_ms=victim_slo_ms,
        ),
        TenantSpec(
            name="aggressor", quota_rps=aggressor_quota,
            burst=max(aggressor_quota / 2.0, 1.0), max_queue=64,
        ),
    ))
    batcher_cfg = BatcherConfig(
        max_batch_size=8, max_wait_us=2_000, max_queue=256,
        tenancy=tenancy,
    )

    def make_request(i: int, phase, tenant: str) -> dict:
        obj = dict(workload.request(i))
        obj["tenant"] = tenant
        return obj

    mode = f"process x{n_workers}" if n_workers else "thread"
    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name=f"serving-selfcheck-tenancy"
    ) as tel:
        if n_workers:
            from photon_ml_tpu.analysis.sanitizers import (
                ProcessLeakSentinel,
            )
            from photon_ml_tpu.serving import shm_model
            from photon_ml_tpu.serving.procpool import WorkerPool
            from photon_ml_tpu.serving.supervisor import ReplicaSupervisor

            with ProcessLeakSentinel(grace_s=15.0, strict=True):
                pool = WorkerPool(
                    workload.model, workload.index_maps,
                    runtime_config=rt_cfg, version=1,
                )
                supervisor = ReplicaSupervisor(
                    pool=pool, n_replicas=n_workers, probe_interval_s=0.1,
                )
                service = ScoringService(supervisor, batcher_cfg)
                with service:
                    report = loadgen.run_noisy_neighbor(
                        service.submit, make_request,
                        victim_rate_rps=40.0, aggressor_rate_rps=40.0,
                    )
                    # Per-tenant counters travel in worker heartbeats;
                    # let one more interval land before snapshotting.
                    time.sleep(3 * pool.heartbeat_interval_s)
                leftover = shm_model.live_segments()
                if leftover:
                    failures.append(
                        "shared segments still mapped after tenancy "
                        f"pass: {leftover}"
                    )
        else:
            runtime = ScoringRuntime(
                workload.model, workload.index_maps, rt_cfg
            )
            service = ScoringService(runtime, batcher_cfg)
            with service:
                report = loadgen.run_noisy_neighbor(
                    service.submit, make_request,
                    victim_rate_rps=40.0, aggressor_rate_rps=40.0,
                )
        snap = tel.snapshot()

    gate = report.isolation(victim_slo_ms)
    if not gate["pass"]:
        failures.append(
            f"noisy-neighbor isolation gate FAILED ({mode}): {gate}"
        )
    counters = snap["counters"]
    if counters.get("serving_tenant_victim_requests_total", 0) < \
            report.victim.completed:
        failures.append(
            "serving_tenant_victim_requests_total = "
            f"{counters.get('serving_tenant_victim_requests_total', 0)}, "
            f"expected >= {report.victim.completed}"
        )
    if counters.get("serving_tenant_aggressor_shed_total", 0) < 1:
        failures.append(
            "serving_tenant_aggressor_shed_total = "
            f"{counters.get('serving_tenant_aggressor_shed_total', 0)}, "
            "expected >= 1 (the burst never pressured the quota)"
        )
    victim_hist = snap["histograms"].get(
        "serving_tenant_victim_request_latency_seconds", {}
    )
    if not victim_hist.get("count"):
        failures.append(
            "no serving_tenant_victim_request_latency_seconds "
            "observations — the per-tenant latency family is dark"
        )
    if not failures:
        print(
            f"serving tenancy selfcheck ({mode}): aggressor burst 10x "
            f"quota shed {report.aggressor.shed} of its requests while "
            f"victim completed {report.victim.completed} with 0 "
            f"failures, p99 {gate['victim_p99_ms']} ms <= SLO "
            f"{victim_slo_ms:g} ms"
        )
    return failures


def run_selfcheck_fleet(out_dir: str, n_workers: int = 0) -> list[str]:
    """Fleet pass: N whole HOSTS behind one FleetRouter, leases from a
    QuotaCoordinator — both ISSUE gates (serving/fleet.py):

    - ``host_kill`` at >= 120 rps: a host's listener dies mid-phase and
      comes back; ZERO failed requests and ZERO rejections for the
      in-quota tenant (a dying host may delay a request, never lose it).
    - ``quota_partition``: every host's LeaseClient loses the
      coordinator mid-phase; fleet-wide admitted rate stays within one
      lease window of the budget (never unlimited, never zero), and
      exact enforcement resumes after heal.  Zero non-shed failures.

    ``n_workers=0`` runs 3 thread-mode hosts; >0 runs 2 hosts each
    backed by ``n_workers`` crash-isolated worker processes (the lease
    crosses the worker wire protocol to bite).  Returns failure strings
    (empty = pass)."""
    import time

    from photon_ml_tpu import telemetry as telemetry_mod
    from photon_ml_tpu.serving import loadgen
    from photon_ml_tpu.serving.batcher import BatcherConfig
    from photon_ml_tpu.serving.fleet import (
        FleetBudget,
        FleetRouter,
        LocalHost,
        QuotaCoordinator,
    )
    from photon_ml_tpu.serving.runtime import RuntimeConfig, ScoringRuntime
    from photon_ml_tpu.serving.service import ScoringService
    from photon_ml_tpu.serving.synthetic import SyntheticWorkload
    from photon_ml_tpu.serving.tenancy import TenancyConfig, TenantSpec

    failures: list[str] = []
    n_hosts = 2 if n_workers else 3
    mode = f"process x{n_workers}/host" if n_workers else "thread"
    kill_rate = 120.0       # the ISSUE floor: >= 120 rps offered
    # Two budgeted tenants: "acme" is IN-quota at kill_rate (the
    # host_kill gate must see zero rejections), "metered" is the
    # over-subscribed tenant whose enforcement the partition gate
    # measures.
    acme_budget_rps = 600.0
    budget_rps = 60.0       # quota_partition fleet budget ("metered")
    burst_s = 0.25          # lease burst = rate * burst_s
    lease_ttl_s = 1.0       # "one lease window"
    workload = SyntheticWorkload(n_entities=64, seed=11)
    rt_cfg = RuntimeConfig(max_batch_size=8, hot_entities=16)
    # Static specs = the pre-lease defaults: each tenant's per-host
    # slice of its fleet budget, so enforcement is budget-shaped even
    # before the first lease lands (and after a batcher rebuild, until
    # re-apply).
    tenancy = TenancyConfig(tenants=(
        TenantSpec(
            name="acme",
            quota_rps=acme_budget_rps / n_hosts,
            burst=max(acme_budget_rps * burst_s / n_hosts, 1.0),
            max_queue=256,
        ),
        TenantSpec(
            name="metered",
            quota_rps=budget_rps / n_hosts,
            burst=max(budget_rps * burst_s / n_hosts, 1.0),
            max_queue=256,
        ),
    ))
    batcher_cfg = BatcherConfig(
        max_batch_size=8, max_wait_us=2_000, max_queue=512,
        tenancy=tenancy,
    )

    def build_host(i: int) -> LocalHost:
        if n_workers:
            from photon_ml_tpu.serving.procpool import WorkerPool
            from photon_ml_tpu.serving.supervisor import ReplicaSupervisor

            pool = WorkerPool(
                workload.model, workload.index_maps,
                runtime_config=rt_cfg, version=1,
            )
            unit = ReplicaSupervisor(
                pool=pool, n_replicas=n_workers, probe_interval_s=0.1,
            )
        else:
            unit = ScoringRuntime(
                workload.model, workload.index_maps, rt_cfg
            )
        return LocalHost(f"host{i}", ScoringService(unit, batcher_cfg))

    def make_request(i: int, phase, tenant: str) -> dict:
        obj = dict(workload.request(i))
        obj["tenant"] = tenant
        return obj

    with telemetry_mod.Telemetry(
        output_dir=out_dir, run_name="serving-selfcheck-fleet"
    ) as tel:
        hosts = [build_host(i).start() for i in range(n_hosts)]
        coordinator = QuotaCoordinator(
            [
                FleetBudget("acme", acme_budget_rps, burst_s=burst_s),
                FleetBudget("metered", budget_rps, burst_s=burst_s),
            ],
            lease_ttl_s=lease_ttl_s,
        )
        clients = [
            h.attach_lease_client(coordinator).start() for h in hosts
        ]
        router = FleetRouter(
            [h.base_url for h in hosts], probe_interval_s=0.1,
        ).start()
        try:
            # Warm every host (compile the bucket ladder) and let the
            # lease shares settle before any gate measures.
            for h_i in range(n_hosts * 4):
                router.score(make_request(h_i, None, "acme"))
            time.sleep(3 * lease_ttl_s / 2)

            # -- gate 1: host_kill at >= 120 rps --------------------------
            report = loadgen.run_fleet_scenario(
                router.submit, make_request,
                loadgen.SCENARIOS["host_kill"], tenant="acme",
                base_rate_rps=kill_rate,
                actions={
                    "kill_host": hosts[0].kill,
                    "restart_host": hosts[0].restart,
                },
            )
            if report.failed:
                failures.append(
                    f"host_kill ({mode}): {report.failed} FAILED "
                    f"requests (must be 0): {report.snapshot()}"
                )
            if report.shed:
                failures.append(
                    f"host_kill ({mode}): {report.shed} rejections for "
                    f"the in-quota tenant (must be 0): "
                    f"{report.snapshot()}"
                )
            if report.completed < kill_rate:  # ~1s of traffic, floor
                failures.append(
                    f"host_kill ({mode}): only {report.completed} "
                    "requests completed — the scenario never loaded "
                    "the fleet"
                )
            snap = tel.snapshot()
            counters = snap["counters"]
            if counters.get("serving_fleet_host_down_total", 0) < 1:
                failures.append(
                    "host_kill: serving_fleet_host_down_total = 0 — "
                    "the router never noticed the kill"
                )
            if counters.get("serving_fleet_resubmitted_total", 0) < 1:
                failures.append(
                    "host_kill: serving_fleet_resubmitted_total = 0 — "
                    "no request was ever resubmitted to a peer"
                )
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if router.healthy_count == n_hosts:
                    break
                time.sleep(0.05)
            if router.healthy_count != n_hosts:
                failures.append(
                    f"host_kill ({mode}): killed host never rejoined "
                    f"({router.healthy_count}/{n_hosts} healthy): "
                    f"{router.healthz()}"
                )

            # -- gate 2: quota_partition ----------------------------------
            def partition() -> bool:
                for lc in clients:
                    lc.partitioned = True
                return True

            def heal() -> bool:
                for lc in clients:
                    lc.partitioned = False
                return True

            q_report = loadgen.run_fleet_scenario(
                router.submit, make_request,
                loadgen.SCENARIOS["quota_partition"], tenant="metered",
                base_rate_rps=2.5 * budget_rps,
                actions={"partition": partition, "heal": heal},
                seed=1,
            )
            if q_report.failed:
                failures.append(
                    f"quota_partition ({mode}): {q_report.failed} "
                    "non-shed FAILURES (sheds are the design working; "
                    f"failures are not): {q_report.snapshot()}"
                )
            burst_total = budget_rps * burst_s
            for pname in ("baseline", "partition", "heal"):
                pr = q_report.phase(pname)
                _, duration, offered, _ = next(
                    row for row in q_report.phases if row[0] == pname
                )
                # Admission bound: the budget over the phase, plus the
                # fleet burst capacity, plus one lease window of
                # over-admission while partitioned (the contract:
                # degrade to the LAST lease, never unlimited).
                window = lease_ttl_s if pname == "partition" else 0.0
                bound = (
                    budget_rps * (duration + window) * 1.15
                    + burst_total + 10
                )
                if pr.completed > bound:
                    failures.append(
                        f"quota_partition ({mode}) phase {pname}: "
                        f"admitted {pr.completed} > bound {bound:.0f} "
                        f"(budget {budget_rps:g} rps over "
                        f"{duration:g}s + one lease window) — "
                        "enforcement leaked past the lease contract"
                    )
                if pr.completed < 0.4 * budget_rps * duration:
                    failures.append(
                        f"quota_partition ({mode}) phase {pname}: "
                        f"admitted only {pr.completed} — degraded "
                        "toward zero (the contract is never-zero)"
                    )
            if str(q_report.actions.get("partition")).startswith("ERROR"):
                failures.append(
                    f"partition action failed: {q_report.actions}"
                )
            stale_now = [lc.stale for lc in clients]
            if any(stale_now):
                failures.append(
                    f"after heal: lease clients still stale "
                    f"({stale_now}) — renewal never recovered"
                )
            if not all(lc.renew_failures > 0 for lc in clients):
                failures.append(
                    "partition never bit: some lease client saw zero "
                    f"renew failures "
                    f"({[lc.renew_failures for lc in clients]})"
                )
            snap = tel.snapshot()
        finally:
            router.stop()
            for h in hosts:
                h.stop()
        counters = snap["counters"]
        if counters.get(
            "serving_fleet_lease_renew_failures_total", 0
        ) < 1:
            failures.append(
                "serving_fleet_lease_renew_failures_total = 0 — the "
                "partition left no metric trace"
            )
        if counters.get("serving_fleet_lease_grants_total", 0) < n_hosts:
            failures.append(
                "serving_fleet_lease_grants_total = "
                f"{counters.get('serving_fleet_lease_grants_total', 0)}"
                f", expected >= {n_hosts}"
            )
    if not failures:
        print(
            f"serving fleet selfcheck ({mode}): host kill under "
            f"{kill_rate:g} rps cost 0 failures / 0 rejections across "
            f"{report.completed} requests; coordinator partition held "
            f"admission within one {lease_ttl_s:g}s lease window of "
            f"{budget_rps:g} rps and recovered "
            f"({q_report.completed} admitted, {q_report.shed} shed, "
            f"{q_report.failed} failed)"
        )
    return failures


def tenant_report(ts_path: str) -> dict:
    """Summarize the ``serving_tenant_*`` family from a metrics_ts.jsonl
    into per-tenant accounting: request rate, shed/rejected totals, and
    latency percentiles (ROADMAP item 3's accounting-dashboard tail).

    Rates are counter deltas over the sampled ``t_mono`` span; p50/p99
    come from the LAST record's latency-histogram summary (cumulative
    over the run).  Returns the JSON-able report dict."""
    from photon_ml_tpu.telemetry.timeseries import read_series

    records = read_series(ts_path)
    if not records:
        raise ValueError(f"no time-series records in {ts_path}")
    first, last = records[0], records[-1]
    span_s = max(float(last["t_mono"]) - float(first["t_mono"]), 1e-9)
    slug_re = __import__("re").compile(
        r"^serving_tenant_([a-z0-9_]+?)_requests_total$"
    )
    tenants = sorted(
        m.group(1)
        for name in last.get("counters", {})
        for m in [slug_re.match(name)]
        if m is not None
    )

    def delta(name: str) -> float:
        return float(last["counters"].get(name, 0)) - float(
            first["counters"].get(name, 0)
        )

    report = {
        "path": ts_path,
        "span_seconds": round(span_s, 3),
        "records": len(records),
        "tenants": {},
    }
    for slug in tenants:
        prefix = f"serving_tenant_{slug}_"
        hist = last.get("histograms", {}).get(
            prefix + "request_latency_seconds"
        ) or {}
        requests = delta(prefix + "requests_total")
        shed = delta(prefix + "shed_total")
        report["tenants"][slug] = {
            "requests": int(requests),
            "rps": round(requests / span_s, 2),
            "shed": int(shed),
            "shed_rps": round(shed / span_s, 2),
            "rejected": int(delta(prefix + "rejected_total")),
            "completed": int(hist.get("count") or 0),
            "latency_p50_ms": (
                None if hist.get("p50") is None
                else round(hist["p50"] * 1e3, 3)
            ),
            "latency_p99_ms": (
                None if hist.get("p99") is None
                else round(hist["p99"] * 1e3, 3)
            ),
        }
    return report


def tenant_report_multi(ts_paths) -> dict:
    """Fleet-grain tenant accounting: one :func:`tenant_report` per
    metrics_ts.jsonl (one file per host), keyed by the host identity the
    sampler recorded (falling back to the file name when two hosts
    collide or a pre-PR-17 file carries none), plus a fleet-wide fold —
    additive columns sum, latency percentiles report the WORST host
    (the number a fleet SLO is judged on).  A single path keeps the
    original single-host report shape."""
    paths = list(ts_paths)
    if len(paths) == 1:
        return tenant_report(paths[0])
    from photon_ml_tpu.telemetry.timeseries import read_series

    hosts: dict = {}
    for path in paths:
        rep = tenant_report(path)
        records = read_series(path)
        host_id = None
        for rec in reversed(records):
            identity = rec.get("host")
            if isinstance(identity, dict) and identity.get("host_id"):
                host_id = str(identity["host_id"])
                break
        key = host_id or os.path.basename(os.path.dirname(path)) or path
        if key in hosts:
            key = f"{key}:{path}"
        hosts[key] = rep

    fleet: dict = {}
    for rep in hosts.values():
        for slug, row in rep["tenants"].items():
            agg = fleet.setdefault(slug, {
                "requests": 0, "rps": 0.0, "shed": 0, "shed_rps": 0.0,
                "rejected": 0, "completed": 0, "hosts": 0,
                "latency_p50_ms": None, "latency_p99_ms": None,
            })
            agg["hosts"] += 1
            for col in ("requests", "shed", "rejected", "completed"):
                agg[col] += row[col]
            for col in ("rps", "shed_rps"):
                agg[col] = round(agg[col] + row[col], 2)
            for col in ("latency_p50_ms", "latency_p99_ms"):
                if row[col] is not None:
                    agg[col] = (
                        row[col] if agg[col] is None
                        else max(agg[col], row[col])
                    )
    return {
        "hosts": hosts,
        "fleet": {"tenants": fleet},
    }


# ---------------------------------------------------------------------------
# Entry point
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    args = build_arg_parser().parse_args(argv)

    if args.tenant_report:
        try:
            report = tenant_report_multi(args.tenant_report)
        except (OSError, ValueError) as exc:
            print(f"tenant report failed: {exc}", file=sys.stderr)
            return 1
        print(json.dumps(report, indent=2))
        return 0

    if args.fleet_join or args.fleet_drain:
        # Admin verbs against the discovery plane: membership is the
        # source of truth, the MembershipWatcher converges the router.
        if not args.membership:
            print(
                "--fleet-join / --fleet-drain need --membership "
                "REGISTRY_URL (the registry is the source of truth; "
                "the watcher converges the router)",
                file=sys.stderr,
            )
            return 2
        from photon_ml_tpu.cluster import RegistryClient

        client = RegistryClient(args.membership)
        if args.fleet_join:
            url = args.fleet_join.rstrip("/")
            hid = args.host_id or url.split("//", 1)[-1]
            member = client.register(hid, url)
            print(json.dumps({"joined": member}, indent=2))
        if args.fleet_drain:
            ok = client.drain(args.fleet_drain)
            print(json.dumps(
                {"drained": bool(ok), "host_id": args.fleet_drain},
                indent=2,
            ))
            if not ok:
                print(
                    f"host id {args.fleet_drain!r} is not a member",
                    file=sys.stderr,
                )
                return 1
        return 0

    if args.selfcheck:
        def both(root: str) -> list[str]:
            # Separate output dirs: each pass owns its Telemetry hub and
            # its metrics.json (the HA assertions read ha/metrics.json).
            single, ha, tenancy, fleet = (
                os.path.join(root, "single"), os.path.join(root, "ha"),
                os.path.join(root, "tenancy"),
                os.path.join(root, "fleet"),
            )
            os.makedirs(single, exist_ok=True)
            os.makedirs(ha, exist_ok=True)
            os.makedirs(tenancy, exist_ok=True)
            os.makedirs(fleet, exist_ok=True)
            return (
                run_selfcheck(single)
                + run_selfcheck_ha(ha)
                + run_selfcheck_tenancy(tenancy)
                + run_selfcheck_fleet(fleet)
            )

        def process(root: str) -> list[str]:
            proc = os.path.join(root, "proc")
            tenancy = os.path.join(root, "tenancy")
            fleet = os.path.join(root, "fleet")
            os.makedirs(proc, exist_ok=True)
            os.makedirs(tenancy, exist_ok=True)
            os.makedirs(fleet, exist_ok=True)
            return (
                run_selfcheck_process(proc, n_workers=args.workers)
                + run_selfcheck_tenancy(tenancy, n_workers=args.workers)
                + run_selfcheck_fleet(fleet, n_workers=args.workers)
            )

        runner = process if args.workers else both
        if args.output_dir:
            os.makedirs(args.output_dir, exist_ok=True)
            failures = runner(args.output_dir)
        else:
            with tempfile.TemporaryDirectory(
                prefix="photon_serving_selfcheck_"
            ) as td:
                failures = runner(td)
        if failures:
            print("serving selfcheck FAILED:", file=sys.stderr)
            for f in failures:
                print(f"  - {f}", file=sys.stderr)
            return 1
        print("serving selfcheck PASSED")
        return 0

    from photon_ml_tpu import telemetry as telemetry_mod

    tel = telemetry_mod.Telemetry(
        output_dir=args.output_dir,
        enabled=args.telemetry != "off",
        run_name="serving",
        sinks=None if args.output_dir else [],
    )
    with tel:
        service, workload = _make_service(args)
        plane_ctx = telemetry_mod.mount_ops_plane(
            tel, port=args.metrics_port,
            interval_s=args.metrics_interval_s,
            readiness=service.readiness,
        )
        with plane_ctx as plane:
            if plane.port is not None:
                print(
                    f"metrics on http://127.0.0.1:{plane.port} "
                    "(/metrics /snapshot /healthz /livez /readyz)",
                    flush=True,
                )
            return _run_service(args, service, workload)


def _run_service(args, service, workload) -> int:
    if args.loadgen:
        from photon_ml_tpu.serving import loadgen

        if workload is None:
            from photon_ml_tpu.serving.synthetic import SyntheticWorkload

            workload = SyntheticWorkload(n_entities=10_000)
        with service:
            if args.loadgen == "closed":
                report = loadgen.closed_loop(
                    service.submit, workload.request,
                    clients=args.clients, duration_s=args.duration,
                )
            else:
                report = loadgen.open_loop(
                    service.submit, workload.request,
                    rate_rps=args.rate, duration_s=args.duration,
                )
        print(json.dumps({
            "loadgen": report.snapshot(),
            "stats": service.stats(),
        }, indent=2))
        return 0

    from photon_ml_tpu.serving.service import start_http_server

    with service:
        server, thread = start_http_server(
            service, host=args.host, port=args.port
        )
        host, port = server.server_address[:2]
        ingress = None
        if args.shm_ingress is not None:
            from photon_ml_tpu.serving.shm_ingress import ShmIngress

            ingress = ShmIngress(
                service, name=args.shm_ingress or None
            ).start()
            print(
                f"shm ingress ring {ingress.name!r} "
                f"({ingress.n_slots} slots x {ingress.slot_bytes} bytes)",
                flush=True,
            )
        agent = None
        if args.membership:
            from photon_ml_tpu.cluster import HeartbeatAgent

            hid = args.host_id or f"{host}:{port}"
            agent = HeartbeatAgent(
                args.membership, hid, f"http://{host}:{port}"
            ).start()
            print(
                f"membership: {hid!r} registered with "
                f"{args.membership}, heartbeating every "
                f"{agent.interval_s:g}s",
                flush=True,
            )
        print(
            f"serving on http://{host}:{port} "
            f"(/score /reload /healthz /livez /readyz /stats); "
            "Ctrl-C to stop",
            flush=True,
        )
        try:
            thread.join()
        except KeyboardInterrupt:
            print("shutting down")
        finally:
            if agent is not None:
                # Graceful exit: drain first so the watcher finishes
                # in-flight work, then leave the member set outright.
                try:
                    agent.client.drain(agent.host_id)
                except Exception:  # noqa: BLE001 — expiry catches up
                    pass
                agent.stop(leave=True)
            if ingress is not None:
                ingress.stop()
            server.shutdown()
            server.server_close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
