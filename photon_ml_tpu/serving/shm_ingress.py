"""Same-machine shared-memory ingress: the zero-HTTP fast path.

A co-located client (a sidecar, a feature service, a gateway sharing
the host) should not pay TCP + HTTP framing to reach a scoring
process a context switch away.  This module runs a fixed ring of
request/response slots in one POSIX shared-memory segment — the same
``multiprocessing.shared_memory`` machinery the zero-copy model pool
uses (serving/shm_model.py) — and carries serving/wire.py frames as
the payloads, so the shm path and the HTTP binary path decode through
the SAME codec and produce bitwise-identical scores.

Layout (all little-endian)::

    ring header  <4s magic "PHSI"> <u16 version> <u16 reserved>
                 <u32 n_slots> <u32 slot_bytes> <u32 publisher_pid>
    slot[i]      <u32 state> <u32 seq> <u32 length> <u32 reserved>
                 <u64 trace> <u64 span> <u32 flags>
                 + slot_bytes of payload

Ring version 2 grew the per-slot trace-context words (trace / span /
flags — :meth:`~photon_ml_tpu.telemetry.core.TraceContext.to_words`):
a client inside a traced request writes its propagated context before
flipping the slot to REQUEST, and the server adopts it around scoring
so the shm hop's spans stitch into the caller's distributed trace.
All-zero words (``trace == 0``) mean "untraced" and cost nothing.

Slot states walk ``FREE → REQUEST → BUSY → RESPONSE → FREE``: the
client owns a FREE slot, writes a request frame, flips it to REQUEST;
the server's poll thread claims it (BUSY), scores through the regular
:meth:`~photon_ml_tpu.serving.service.ScoringService.score_many` path
(admission, batching, tenancy — the shm path skips HTTP, not policy),
writes a response frame, flips to RESPONSE; the client reads it back
and frees the slot.  The ``seq`` counter increments per use so a
late reader can never mistake a stale response for its own.

Writes are ordered payload → length/seq → state, and each header
field is one aligned 32-bit store, so a reader that observes the
state flip observes the fields behind it.  Multiple client PROCESSES
must be handed disjoint ``slot_range``s — slot acquisition is
lock-free only within a process (a lock guards the local free list).

The server's poll loop backs off adaptively: it spins at ~50 µs while
traffic flows and decays to 2 ms when idle, so an idle ring costs
near-zero CPU without adding tail latency under load.
"""

from __future__ import annotations

import multiprocessing
import os
import struct
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from multiprocessing import shared_memory
from typing import Optional, Sequence

from photon_ml_tpu.serving import wire as wire_mod
from photon_ml_tpu.serving.batcher import DeadlineExceededError, RejectedError
from photon_ml_tpu import telemetry as telemetry_mod

__all__ = ["ShmIngress", "ShmIngressClient", "ShmIngressError"]

_RING_HEADER = struct.Struct("<4sHHIII")
_SLOT_HEADER = struct.Struct("<IIIIQQI")
#: the trace-context words alone, at offset 16 inside the slot header
#: (after the four aligned u32 control fields, whose offsets v2 keeps).
_TRACE_WORDS = struct.Struct("<QQI")
_MAGIC = b"PHSI"
_VERSION = 2

#: slot states
_FREE, _REQUEST, _BUSY, _RESPONSE = 0, 1, 2, 3

_U32 = struct.Struct("<I")

#: idle poll backoff bounds (seconds): spin fast under load, decay
#: when the ring is quiet.
_MIN_POLL_S = 50e-6
_MAX_POLL_S = 2e-3


class ShmIngressError(RuntimeError):
    """The ring is unusable: bad magic/version on attach, a frame too
    large for its slot, or the segment disappeared."""


def _slot_offsets(i: int, slot_bytes: int) -> tuple:
    """(header_off, payload_off) for slot ``i``."""
    base = _RING_HEADER.size + i * (_SLOT_HEADER.size + slot_bytes)
    return base, base + _SLOT_HEADER.size


class ShmIngress:
    """Server side: owns the segment, polls for requests, scores them
    through ``service`` and answers in place.

    Parameters
    ----------
    service:
        The :class:`~photon_ml_tpu.serving.service.ScoringService` to
        score through — same parser, same admission, same batcher as
        the HTTP paths.
    n_slots / slot_bytes:
        Ring geometry.  One slot holds one request frame and, later,
        its response frame; size slots for your largest batch.
    workers:
        Concurrent scoring handlers.  More than one lets requests from
        different slots coalesce into shared device batches.
    """

    def __init__(
        self,
        service,
        n_slots: int = 16,
        slot_bytes: int = 1 << 20,
        name: Optional[str] = None,
        workers: int = 4,
    ):
        if n_slots < 1:
            raise ValueError(f"shm ingress needs n_slots >= 1, got {n_slots}")
        if slot_bytes < 4096:
            raise ValueError(
                f"shm ingress needs slot_bytes >= 4096, got {slot_bytes}"
            )
        if workers < 1:
            raise ValueError(f"shm ingress needs workers >= 1, got {workers}")
        self.service = service
        self.n_slots = n_slots
        self.slot_bytes = slot_bytes
        self._workers = workers
        size = _RING_HEADER.size + n_slots * (_SLOT_HEADER.size + slot_bytes)
        self._shm = shared_memory.SharedMemory(
            create=True, size=size, name=name
        )
        _RING_HEADER.pack_into(
            self._shm.buf, 0, _MAGIC, _VERSION, 0, n_slots, slot_bytes,
            os.getpid(),
        )
        for i in range(n_slots):
            off, _ = _slot_offsets(i, slot_bytes)
            _SLOT_HEADER.pack_into(
                self._shm.buf, off, _FREE, 0, 0, 0, 0, 0, 0
            )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._pool: Optional[ThreadPoolExecutor] = None

    @property
    def name(self) -> str:
        """Segment name a co-located client attaches by."""
        return self._shm.name

    def start(self) -> "ShmIngress":
        if self._thread is not None:
            return self
        self._pool = ThreadPoolExecutor(
            max_workers=self._workers, thread_name_prefix="shm-ingress"
        )
        self._thread = threading.Thread(
            target=self._poll_loop, name="shm-ingress-poll", daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
        if self._pool is not None:
            self._pool.shutdown(wait=True)
            self._pool = None
        try:
            self._shm.close()
            self._shm.unlink()
        except (OSError, FileNotFoundError):
            pass

    # -- poll loop ---------------------------------------------------------
    def _poll_loop(self) -> None:
        backoff = _MIN_POLL_S
        buf = self._shm.buf
        while not self._stop.is_set():
            claimed = False
            for i in range(self.n_slots):
                off, _ = _slot_offsets(i, self.slot_bytes)
                (state,) = _U32.unpack_from(buf, off)
                if state != _REQUEST:
                    continue
                _U32.pack_into(buf, off, _BUSY)
                claimed = True
                self._pool.submit(self._handle_slot, i)
            if claimed:
                backoff = _MIN_POLL_S
                continue
            self._stop.wait(backoff)
            backoff = min(backoff * 2, _MAX_POLL_S)

    def _handle_slot(self, i: int) -> None:
        tel = telemetry_mod.current()
        buf = self._shm.buf
        off, data_off = _slot_offsets(i, self.slot_bytes)
        (_state, seq, length, _res,
         trace_w, span_w, flags) = _SLOT_HEADER.unpack_from(buf, off)
        # Trace adoption from the slot header's words: the handler's
        # spans (and the batcher's serving.batch span downstream) parent
        # to the CLIENT's span, so the shm hop rides the caller's
        # distributed trace.  Zero words = untraced, ctx = None.
        ctx = telemetry_mod.TraceContext.from_words(trace_w, span_w, flags)
        payload = bytes(buf[data_off:data_off + min(length, self.slot_bytes)])
        tel.counter("serving_ingress_rx_bytes").inc(len(payload))
        n_rows = 1
        try:
            with tel.adopt(ctx):
                rows = wire_mod.decode_request(
                    payload, self.service.request_parser()
                )
                n_rows = len(rows)
                tel.counter("serving_ingress_requests_total").inc()
                tel.counter("serving_ingress_rows_total").inc(n_rows)
                results = self.service.score_many(rows)
        except Exception as exc:  # noqa: BLE001 — answer in-band
            tel.counter("serving_ingress_errors_total").inc()
            kind = (
                "rejected" if isinstance(exc, RejectedError)
                else "deadline" if isinstance(exc, DeadlineExceededError)
                else "bad_request" if isinstance(exc, ValueError)
                else "internal"
            )
            results = [{"error": str(exc), "kind": kind}] * n_rows
        t_encode = time.perf_counter()
        frame = wire_mod.encode_response(results)
        tel.histogram("serving_stage_encode_seconds").observe(
            time.perf_counter() - t_encode
        )
        if len(frame) > self.slot_bytes:
            tel.counter("serving_ingress_errors_total").inc()
            overflow = {
                "error": (
                    f"response frame ({len(frame)} bytes) exceeds the "
                    f"{self.slot_bytes}-byte slot; use fewer rows per "
                    "request or a larger ring"
                ),
                "kind": "internal",
            }
            frame = wire_mod.encode_response([overflow] * len(results))
            if len(frame) > self.slot_bytes:
                frame = wire_mod.encode_response([overflow])
        tel.counter("serving_ingress_tx_bytes").inc(len(frame))
        buf[data_off:data_off + len(frame)] = frame
        _U32.pack_into(buf, off + 8, len(frame))
        _U32.pack_into(buf, off + 4, seq)
        _U32.pack_into(buf, off, _RESPONSE)


class ShmIngressClient:
    """Client side: attach by name, submit request frames, block for
    responses.  One instance is thread-safe; separate PROCESSES need
    disjoint ``slot_range``s (e.g. process 0 takes ``(0, 8)``,
    process 1 ``(8, 16)``)."""

    def __init__(
        self, name: str, slot_range: Optional[tuple] = None
    ):
        try:
            self._shm = shared_memory.SharedMemory(name=name)
        except FileNotFoundError:
            raise ShmIngressError(
                f"shm ingress segment {name!r} is gone — is the server "
                "running on this machine?"
            ) from None
        if self._shm.size < _RING_HEADER.size:
            raise ShmIngressError(
                f"segment {name!r} is {self._shm.size} bytes — smaller "
                "than a ring header; not an ingress ring"
            )
        (magic, version, _res, n_slots, slot_bytes,
         publisher_pid) = _RING_HEADER.unpack_from(self._shm.buf, 0)
        if magic != _MAGIC:
            raise ShmIngressError(
                f"segment {name!r} has magic {bytes(magic)!r}; not an "
                "ingress ring"
            )
        if version != _VERSION:
            raise ShmIngressError(
                f"ring version {version} unsupported (this build speaks "
                f"{_VERSION})"
            )
        # A STANDALONE attacher must drop the resource-tracker
        # registration or its exit unlinks the server's ring out from
        # under it; the publisher itself and multiprocessing children
        # (shared tracker daemon) must NOT — shm_model.py documents the
        # Python 3.10 behavior this mirrors.
        if (
            os.getpid() != publisher_pid
            and multiprocessing.parent_process() is None
        ):
            try:
                from multiprocessing import resource_tracker
                resource_tracker.unregister(self._shm._name, "shared_memory")
            except Exception:  # noqa: BLE001 — tracker internals vary
                pass
        self.n_slots = n_slots
        self.slot_bytes = slot_bytes
        lo, hi = slot_range if slot_range is not None else (0, n_slots)
        if not (0 <= lo < hi <= n_slots):
            raise ValueError(
                f"slot_range {slot_range} out of bounds for a "
                f"{n_slots}-slot ring"
            )
        self._lock = threading.Lock()
        self._free = list(range(lo, hi))
        self._zombies: set = set()

    # -- slot bookkeeping --------------------------------------------------
    def _acquire(self, deadline: float) -> int:
        while True:
            with self._lock:
                # Reclaim zombies whose server-side work has finished:
                # a RESPONSE (or re-FREE) state means nobody is writing.
                for z in list(self._zombies):
                    off, _ = _slot_offsets(z, self.slot_bytes)
                    (state,) = _U32.unpack_from(self._shm.buf, off)
                    if state in (_RESPONSE, _FREE):
                        _U32.pack_into(self._shm.buf, off, _FREE)
                        self._zombies.discard(z)
                        self._free.append(z)
                if self._free:
                    return self._free.pop()
            if time.monotonic() > deadline:
                raise DeadlineExceededError(
                    "DEADLINE_EXCEEDED: no free ingress slot before the "
                    "deadline"
                )
            time.sleep(_MIN_POLL_S)

    # -- scoring -----------------------------------------------------------
    def score_many(
        self, requests: Sequence[dict], timeout_s: float = 30.0
    ) -> list:
        """Encode JSON-shaped requests, ride the ring, decode results —
        the same per-row result dicts the HTTP paths return."""
        frame = wire_mod.encode_request(requests)
        return self._roundtrip(frame, timeout_s)

    def score(self, request: dict, timeout_s: float = 30.0) -> dict:
        return self.score_many([request], timeout_s=timeout_s)[0]

    def _roundtrip(self, frame: bytes, timeout_s: float) -> list:
        if len(frame) > self.slot_bytes:
            raise ShmIngressError(
                f"request frame ({len(frame)} bytes) exceeds the "
                f"{self.slot_bytes}-byte slot; split the batch or size "
                "the ring larger"
            )
        deadline = time.monotonic() + timeout_s
        i = self._acquire(deadline)
        buf = self._shm.buf
        off, data_off = _slot_offsets(i, self.slot_bytes)
        (_state, seq, _len, _res,
         _tw, _sw, _fl) = _SLOT_HEADER.unpack_from(buf, off)
        seq = (seq + 1) & 0xFFFFFFFF
        buf[data_off:data_off + len(frame)] = frame
        # Trace-context words ride the slot header (before the state
        # flip, like the payload): the server parents its handling spans
        # to this caller's span.  No active trace writes zeros.
        pctx = telemetry_mod.current().propagation_context()
        words = pctx.to_words() if pctx is not None else (0, 0, 0)
        _TRACE_WORDS.pack_into(buf, off + 16, *words)
        _U32.pack_into(buf, off + 8, len(frame))
        _U32.pack_into(buf, off + 4, seq)
        _U32.pack_into(buf, off, _REQUEST)
        backoff = _MIN_POLL_S
        try:
            while True:
                (state,) = _U32.unpack_from(buf, off)
                if state == _RESPONSE:
                    (seq_r,) = _U32.unpack_from(buf, off + 4)
                    if seq_r == seq:
                        (length,) = _U32.unpack_from(buf, off + 8)
                        payload = bytes(
                            buf[data_off:data_off
                                + min(length, self.slot_bytes)]
                        )
                        _U32.pack_into(buf, off, _FREE)
                        with self._lock:
                            self._free.append(i)
                        return wire_mod.decode_response(payload)
                if time.monotonic() > deadline:
                    # The server may still be scoring this slot; park it
                    # as a zombie and reclaim once a response lands.
                    with self._lock:
                        self._zombies.add(i)
                    raise DeadlineExceededError(
                        f"DEADLINE_EXCEEDED: no ingress response within "
                        f"{timeout_s:.3f}s"
                    )
                time.sleep(backoff)
                backoff = min(backoff * 2, _MAX_POLL_S)
        except ShmIngressError:
            with self._lock:
                self._free.append(i)
            raise

    def close(self) -> None:
        try:
            self._shm.close()
        except (OSError, BufferError):
            pass
