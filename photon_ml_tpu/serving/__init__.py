"""Online serving: micro-batched low-latency GLM / GAME scoring.

The reference deploys GAME models behind LinkedIn's online scorers (the
per-entity random-effect story of SURVEY.md §0 only pays off when a
request for user *u* can fetch w_u in microseconds); this package is the
TPU-native analogue of that request path over the batch stack:

- :mod:`~photon_ml_tpu.serving.kernels` — the ONE implementation of
  fixed-effect matvec + random-effect gather + offset sum, shared by
  batch scoring (``GameTransformer`` / ``game_scoring_driver``) and the
  online runtime.
- :mod:`~photon_ml_tpu.serving.runtime` — ``ScoringRuntime``: pre-compiled
  jit kernels at a ladder of padded batch-size buckets, a per-entity
  coefficient table with an LRU hot set resident on device, host-side
  fallback gathers for the cold tail.
- :mod:`~photon_ml_tpu.serving.batcher` — ``MicroBatcher``: bounded-queue
  request coalescing under ``max_batch_size`` / ``max_wait_us``, padding
  to the nearest bucket, per-request futures, admission control and
  deadline timeouts classified through ``utils/watchdog``.
- :mod:`~photon_ml_tpu.serving.service` — ``ScoringService`` (in-process
  API) and a stdlib ``ThreadingHTTPServer`` JSON endpoint (``/score``,
  ``/reload``, ``/healthz``, ``/livez``, ``/readyz``, ``/stats``).
- :mod:`~photon_ml_tpu.serving.supervisor` — ``ReplicaSupervisor``: N
  replicas behind one listener, health probes, request resubmission,
  decorrelated-jitter restarts (the HA story; docs/serving.md).
- :mod:`~photon_ml_tpu.serving.swap` — ``HotSwapper``: zero-downtime
  model hot-swap with verified one-step rollback.
- :mod:`~photon_ml_tpu.serving.loadgen` — closed/open-loop load
  generators plus scripted scenarios (diurnal ramp, skew shift,
  swap-under-load, replica-kill, worker-kill, noisy-neighbor;
  ``bench.py bench_serving``).
- :mod:`~photon_ml_tpu.serving.tenancy` — multi-tenant isolation:
  ``TenantSpec`` / ``TenancyConfig`` (per-tenant bulkhead partitions,
  token-bucket quotas, tiered-admission watermarks, p99 SLOs, circuit
  breakers, enforced in the batcher) and ``TenantRouter`` (tenant ->
  model version on the HotSwapper registry, per-tenant hot swap and
  rollback; docs/serving.md "Tenancy").
- :mod:`~photon_ml_tpu.serving.fleet` — the node tier: ``FleetRouter``
  routes requests across N host endpoints (health probes, DOWN-marking,
  peer resubmission, jittered reconnects, connection draining) and
  ``QuotaCoordinator`` / ``LeaseClient`` carve each tenant's FLEET
  budget into short-lived per-host rate leases (demand-aware
  rebalancing, reclaim on host death, degrade-to-last-lease under
  partition; docs/serving.md "Fleet").
- :mod:`~photon_ml_tpu.serving.procpool` /
  :mod:`~photon_ml_tpu.serving.worker` /
  :mod:`~photon_ml_tpu.serving.shm_model` — crash-isolated worker
  PROCESSES behind the same supervisor seams: the model published once
  into POSIX shared memory with verified (sha256) attach, framed
  request/heartbeat protocol, cross-process hot swap
  (``--workers N``; docs/serving.md "Process mode").
- :mod:`~photon_ml_tpu.serving.wire` — the binary data plane: fixed-
  layout, versioned frames of dtype-tagged columns carrying requests,
  responses, and worker-IPC messages with zero-copy decode and bitwise
  score parity against the JSON path (docs/serving.md "Data plane").
- :mod:`~photon_ml_tpu.serving.shm_ingress` — same-machine ingress: a
  shared-memory slot ring carrying wire frames, skipping HTTP entirely
  for co-located clients (``--shm-ingress``).

``python -m photon_ml_tpu.serving --selfcheck`` builds a synthetic GAME
model, serves concurrent HTTP requests, and verifies batched results are
bit-identical to single-request scoring.  See docs/serving.md.

Imports here are lazy: ``game.estimator`` imports ``serving.kernels``
(the shared scoring math), so the package root must not import modules
that import the estimator back.
"""

from __future__ import annotations

_LAZY = {
    "ScoringRuntime": ("photon_ml_tpu.serving.runtime", "ScoringRuntime"),
    "RuntimeConfig": ("photon_ml_tpu.serving.runtime", "RuntimeConfig"),
    "MicroBatcher": ("photon_ml_tpu.serving.batcher", "MicroBatcher"),
    "BatcherConfig": ("photon_ml_tpu.serving.batcher", "BatcherConfig"),
    "RejectedError": ("photon_ml_tpu.serving.batcher", "RejectedError"),
    "DeadlineExceededError": (
        "photon_ml_tpu.serving.batcher", "DeadlineExceededError",
    ),
    "ScoringService": ("photon_ml_tpu.serving.service", "ScoringService"),
    "start_http_server": (
        "photon_ml_tpu.serving.service", "start_http_server",
    ),
    "ReplicaSupervisor": (
        "photon_ml_tpu.serving.supervisor", "ReplicaSupervisor",
    ),
    "WorkerPool": ("photon_ml_tpu.serving.procpool", "WorkerPool"),
    "ProcessReplica": ("photon_ml_tpu.serving.procpool", "ProcessReplica"),
    "ModelMapError": ("photon_ml_tpu.serving.shm_model", "ModelMapError"),
    "TenancyConfig": ("photon_ml_tpu.serving.tenancy", "TenancyConfig"),
    "TenantSpec": ("photon_ml_tpu.serving.tenancy", "TenantSpec"),
    "TenantRouter": ("photon_ml_tpu.serving.tenancy", "TenantRouter"),
    "FleetRouter": ("photon_ml_tpu.serving.fleet", "FleetRouter"),
    "FleetBudget": ("photon_ml_tpu.serving.fleet", "FleetBudget"),
    "QuotaCoordinator": (
        "photon_ml_tpu.serving.fleet", "QuotaCoordinator",
    ),
    "LeaseClient": ("photon_ml_tpu.serving.fleet", "LeaseClient"),
    "LocalHost": ("photon_ml_tpu.serving.fleet", "LocalHost"),
    "HotSwapper": ("photon_ml_tpu.serving.swap", "HotSwapper"),
    "SwapResult": ("photon_ml_tpu.serving.swap", "SwapResult"),
    "SwapInProgressError": (
        "photon_ml_tpu.serving.swap", "SwapInProgressError",
    ),
    "WireFormatError": ("photon_ml_tpu.serving.wire", "WireFormatError"),
    "ShmIngress": ("photon_ml_tpu.serving.shm_ingress", "ShmIngress"),
    "ShmIngressClient": (
        "photon_ml_tpu.serving.shm_ingress", "ShmIngressClient",
    ),
    "ShmIngressError": (
        "photon_ml_tpu.serving.shm_ingress", "ShmIngressError",
    ),
    "HttpSubmitter": ("photon_ml_tpu.serving.loadgen", "HttpSubmitter"),
}

__all__ = sorted(_LAZY)


def __getattr__(name: str):
    entry = _LAZY.get(name)
    if entry is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(entry[0]), entry[1])
