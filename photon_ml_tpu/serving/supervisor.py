"""Replica supervision: N scoring replicas behind one listener.

One ``ScoringRuntime`` is a single point of failure: a wedged dispatch
thread or a lost device is a full outage until something restarts the
process.  :class:`ReplicaSupervisor` runs ``n_replicas`` independent
replicas — each its own ``ScoringRuntime`` + ``MicroBatcher`` (own
dispatch thread, so the runtime's lock-free single-writer invariant
holds per replica) — and owns three jobs:

- **Routing**: requests round-robin over HEALTHY replicas.  A replica
  that fails a request with a watchdog-transient error (the vocabulary a
  crash speaks: UNAVAILABLE, device lost, injected faults) is marked
  down and the request is RESUBMITTED to another healthy replica — the
  client's future only fails when every replica has been tried.  This is
  what makes a scripted replica kill cost zero failed requests.
- **Health probes**: a supervision thread scores a cheap offset-only
  probe through every healthy replica's real dispatch path each
  ``probe_interval_s`` (``bypass_admission=True`` — shedding tiers must
  not read as replica death).  ``probe_failure_threshold`` consecutive
  failures — including a probe future that never completes within
  ``probe_timeout_s``, i.e. a WEDGED dispatch thread — drain the replica.
- **Restarts**: a down replica's batcher is drained and stopped off the
  request path, then rebuilt from ``runtime_factory`` after a
  decorrelated-jitter backoff (``utils/watchdog.RetryPolicy``,
  ``jitter="decorrelated"``: sleep ~ U[base, 3·previous], capped) — N
  replicas lost to one cause do not restart in lockstep and re-overload
  whatever killed them.  Sustained health resets the backoff walk.

Replica states::

    starting ──> healthy ──(probe/request failures)──> down
                    ^                                    │
                    └── restart (factory, jitter backoff)┘

``kill_replica(rid)`` is the scripted crash: the replica's runtime is
replaced with a poison stand-in so every queued and future batch fails
transiently (and resubmits elsewhere), then the replica is marked down
and follows the normal drain → backoff → restart path.  The chaos seam
``serving.replica`` fires at routing time (FaultSpec ``at=k`` kills the
k-th routed request's replica) for plan-scripted kills.

The supervisor intentionally mirrors ``ScoringService``'s surface
(``submit`` / ``healthz`` / ``stats`` / ``start`` / ``stop``) so the
service and HTTP layer compose with either a bare runtime or a
supervisor — see serving/service.py and docs/serving.md.

**Process mode**: pass ``pool=`` (a
:class:`~photon_ml_tpu.serving.procpool.WorkerPool`) instead of
``runtime_factory`` and every replica becomes an OS process mapping the
pool's shared-memory model — same routing, probing, resubmission, and
jittered-restart machinery, but the fault domain a probe failure or
kill costs is a whole process, and ``kill_replica`` delivers a real
SIGKILL.  The pool's :class:`ProcessReplica` duck-types the
MicroBatcher surface the supervisor drives, so every seam below stays
mode-agnostic.
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from concurrent.futures import Future
from typing import Callable, Optional

from photon_ml_tpu import telemetry as telemetry_mod
from photon_ml_tpu.analysis import sanitizers
from photon_ml_tpu.chaos import core as chaos_mod
from photon_ml_tpu.serving.batcher import (
    BatcherConfig,
    DeadlineExceededError,
    MicroBatcher,
    RejectedError,
)
from photon_ml_tpu.serving.runtime import Row, RuntimeConfig, ScoringRuntime
from photon_ml_tpu.utils.watchdog import RetryPolicy


class _DeadRuntime:
    """Poison runtime installed by :meth:`ReplicaSupervisor.kill_replica`:
    every batch fails with a watchdog-transient error, so queued requests
    drain as resubmissions instead of hanging on a corpse."""

    degraded = False

    def __init__(self, reason: str):
        self.reason = reason
        self.model_version = 0
        self.buckets = [1]

    def score_rows(self, rows):
        raise RuntimeError(f"UNAVAILABLE: replica killed ({self.reason})")

    def bucket_for(self, n: int) -> int:
        return n


@dataclasses.dataclass
class _Replica:
    rid: int
    batcher: MicroBatcher
    state: str = "healthy"  # "healthy" | "down"
    probe_failures: int = 0
    restart_attempt: int = 0
    last_delay: Optional[float] = None
    next_restart_t: float = 0.0
    restarts: int = 0
    down_reason: Optional[str] = None


class ReplicaSupervisor:
    """N scoring replicas + health probes + jittered restarts."""

    def __init__(
        self,
        runtime_factory: Optional[Callable[[], ScoringRuntime]] = None,
        n_replicas: int = 2,
        batcher_config: Optional[BatcherConfig] = None,
        policy: Optional[RetryPolicy] = None,
        restart_policy: Optional[RetryPolicy] = None,
        probe_interval_s: float = 0.25,
        probe_timeout_s: float = 10.0,
        probe_failure_threshold: int = 2,
        rng: Optional[random.Random] = None,
        clock: Callable[[], float] = time.monotonic,
        pool=None,
    ):
        if n_replicas < 1:
            raise ValueError("n_replicas must be >= 1")
        if (runtime_factory is None) == (pool is None):
            raise ValueError(
                "pass exactly one of runtime_factory (in-process "
                "replicas) or pool (process workers)"
            )
        self.runtime_factory = runtime_factory
        self.pool = pool
        self.n_replicas = n_replicas
        self.batcher_config = batcher_config
        self.policy = policy or RetryPolicy()
        self.restart_policy = restart_policy or RetryPolicy(
            backoff_seconds=0.05,
            max_backoff_seconds=2.0,
            jitter="decorrelated",
        )
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.probe_failure_threshold = probe_failure_threshold
        self._rng = rng or random.Random()
        self._clock = clock
        self.replicas: list[_Replica] = []
        self._lock = sanitizers.tracked(
            threading.Lock(), "serving.supervisor"
        )
        self._rr = 0
        self._stop = threading.Event()
        self._probe_thread: Optional[threading.Thread] = None
        self._started = False
        #: tenant → (model, index_maps, config, version, path) retained
        #: from tenant-swap commits (thread mode), so a restarted
        #: replica's fresh batcher gets every committed tenant route
        #: re-applied.  Process mode keeps this empty — the pool's
        #: tenant-generation registry replays routes into respawned
        #: workers instead.  Written only under _lock.
        self._tenant_factories: dict = {}
        #: tenant → (rate_rps, burst) live quota overrides (fleet lease
        #: apply path, serving/fleet.py) — HOST-level rates, split
        #: evenly across replicas because each replica admits with its
        #: own bucket.  Replayed into every rebuilt replica so a
        #: restart comes back under the live lease, not the static
        #: spec.  Written only under _lock.
        self._quota_overrides: dict = {}

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "ReplicaSupervisor":
        if self._started:
            return self
        for rid in range(self.n_replicas):
            self.replicas.append(self._build_replica(rid))
        self._stop.clear()
        self._probe_thread = threading.Thread(
            target=self._probe_loop, name="replica-supervisor", daemon=True
        )
        self._probe_thread.start()
        self._started = True
        telemetry_mod.current().gauge(
            "serving_healthy_replicas_count"
        ).set(len(self.replicas))
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        thread = self._probe_thread
        self._probe_thread = None
        if thread is not None:
            thread.join(timeout=timeout)
        for rep in self.replicas:
            rep.batcher.stop(timeout=timeout)
        if self.pool is not None:
            self.pool.close(timeout=timeout)
        if thread is not None and thread.is_alive():
            # The supervision thread outlived the first join: a restart
            # was mid-spawn when stop() began (a worker spawn takes
            # seconds on a loaded box).  new_replica on the now-closed
            # pool refuses — and a spawn that slipped past the close
            # reaps itself at registration — so the thread exits
            # promptly; sweep any batcher it installed before noticing.
            thread.join(timeout=timeout)
            for rep in self.replicas:
                rep.batcher.stop(timeout=1.0)
        self._started = False

    def __enter__(self) -> "ReplicaSupervisor":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.stop()
        return False

    def _build_replica(self, rid: int) -> _Replica:
        if self.pool is not None:
            batcher = self.pool.new_replica(
                rid, self.batcher_config, policy=self.policy
            )
        else:
            runtime = self.runtime_factory()
            batcher = MicroBatcher(
                runtime, self.batcher_config, policy=self.policy
            ).start()
            # Re-apply every committed tenant route so the fresh
            # replica serves tenants on their swapped versions, not the
            # default (serving/tenancy.py).
            with self._lock:
                factories = dict(self._tenant_factories)
            for tenant, (model, index_maps, config, version,
                         path) in factories.items():
                rt = ScoringRuntime(model, index_maps, config)
                rt.model_version = version
                rt.model_path = path
                batcher.set_tenant_route(tenant, rt)
        with self._lock:
            overrides = dict(self._quota_overrides)
        for tenant, (rate, burst) in overrides.items():
            try:
                batcher.set_tenant_quota(
                    tenant, *self._per_replica_quota(rate, burst)
                )
            except Exception:  # noqa: BLE001 — next lease re-applies
                pass
        return _Replica(rid=rid, batcher=batcher)

    def _per_replica_quota(self, rate, burst) -> tuple:
        """A host-level lease split evenly across this host's replicas
        (admission is per-bucket, so N buckets at R/N enforce R — the
        same sizing precedent as per-worker quota specs)."""
        n = max(1, self.n_replicas)
        per_rate = None if rate is None else float(rate) / n
        per_burst = None if burst is None else max(1.0, float(burst) / n)
        return per_rate, per_burst

    def set_tenant_quota(
        self, tenant: str, rate_rps, burst=None
    ) -> None:
        """Apply a HOST-level tenant quota across every replica (fleet
        lease apply path).  Raises if no replica accepted it — e.g. an
        undeclared tenant; partial application heals at the next lease
        renewal, which re-applies the full rate set."""
        with self._lock:
            self._quota_overrides[tenant] = (rate_rps, burst)
            replicas = list(self.replicas)
        per_rate, per_burst = self._per_replica_quota(rate_rps, burst)
        applied = 0
        last_exc: Optional[Exception] = None
        for rep in replicas:
            try:
                rep.batcher.set_tenant_quota(tenant, per_rate, per_burst)
                applied += 1
            except Exception as exc:  # noqa: BLE001 — count failures
                last_exc = exc
        if applied == 0 and last_exc is not None:
            raise last_exc

    # -- routing (any thread) ------------------------------------------------
    def _healthy(self) -> list[_Replica]:
        with self._lock:
            return [r for r in self.replicas if r.state == "healthy"]

    @property
    def healthy_count(self) -> int:
        return len(self._healthy())

    def _pick(self, tried: set) -> Optional[_Replica]:
        with self._lock:
            candidates = [
                r for r in self.replicas
                if r.state == "healthy" and r.rid not in tried
            ]
            if not candidates:
                return None
            self._rr += 1
            return candidates[self._rr % len(candidates)]

    def parse_request(self, obj: dict) -> Row:
        runtime = self._any_runtime()
        if runtime is None:
            raise RejectedError(
                "UNAVAILABLE: no replica available to parse against; "
                "retry with backoff"
            )
        return runtime.parse_request(obj)

    def _any_runtime(self):
        if self.pool is not None:
            # Parsing is parent-side state in process mode (the pool's
            # RequestParser) — no worker round-trip, and it stays
            # available even while every worker is respawning.
            return self.pool.runtime_view()
        # isinstance filter even on healthy replicas: a just-killed one
        # carries a poison _DeadRuntime for the instant before
        # _mark_down lands, and parsing against it would crash.
        reps = [
            r for r in self._healthy()
            if isinstance(r.batcher.runtime, ScoringRuntime)
        ] or [
            r for r in self.replicas
            if isinstance(r.batcher.runtime, ScoringRuntime)
        ]
        return reps[0].batcher.runtime if reps else None

    def submit(
        self, row, timeout_ms: Optional[float] = None
    ) -> Future:
        """Route one parsed row; returns a supervisor-level future.

        The future resolves from whichever replica ultimately scores the
        row — a replica that dies mid-request is drained and the row is
        resubmitted to a peer (fresh deadline budget; failover
        stretches a deadline rather than failing the request).  Only
        exhausting every healthy replica fails the future.
        """
        fut: Future = Future()
        self._route(row, timeout_ms, fut, tried=set())
        return fut

    def _route(
        self, row, timeout_ms, fut: Future, tried: set
    ) -> None:
        last_reject: Optional[Exception] = None
        while True:
            rep = self._pick(tried)
            if rep is None:
                exc = last_reject or RejectedError(
                    "UNAVAILABLE: no healthy replica "
                    f"({self.healthy_count} healthy, "
                    f"{len(tried)} tried); retry with backoff"
                )
                if fut.set_running_or_notify_cancel():
                    fut.set_exception(exc)
                return
            try:
                # The scripted-crash seam: a fault here is a replica
                # dying as it picks up the request (docs/robustness.md).
                chaos_mod.maybe_fail("serving.replica", replica=rep.rid)
                inner = rep.batcher.submit(row, timeout_ms=timeout_ms)
            except RejectedError as exc:
                # This replica's admission control shed the row; another
                # replica below its watermarks may still take it.
                tried.add(rep.rid)
                last_reject = exc
                continue
            except Exception as exc:  # noqa: BLE001 — classified below
                if not self.policy.classify(exc).transient:
                    if fut.set_running_or_notify_cancel():
                        fut.set_exception(exc)
                    return
                self._mark_down(
                    rep, f"failed at routing: {exc}"[:200]
                )
                tried.add(rep.rid)
                telemetry_mod.current().counter(
                    "serving_resubmitted_total"
                ).inc()
                continue
            inner.add_done_callback(
                lambda f, rep=rep: self._on_done(
                    f, rep, row, timeout_ms, fut, tried
                )
            )
            return

    def _on_done(
        self, inner: Future, rep: _Replica, row, timeout_ms,
        fut: Future, tried: set,
    ) -> None:
        # Runs on the replica's dispatch thread — must never join
        # threads or block; resubmission is a non-blocking queue put.
        exc = inner.exception()
        if exc is None:
            if fut.set_running_or_notify_cancel():
                fut.set_result(inner.result())
            return
        if (
            isinstance(exc, (DeadlineExceededError, RejectedError))
            or not self.policy.classify(exc).transient
        ):
            # The REQUEST's own verdict (expired deadline, bad input) —
            # another replica would only repeat it.
            if fut.set_running_or_notify_cancel():
                fut.set_exception(exc)
            return
        # A transient failure is the replica's fault, not the row's:
        # drain the replica, resubmit the row to a peer.
        self._mark_down(rep, f"failed a request: {exc}"[:200])
        tried.add(rep.rid)
        telemetry_mod.current().counter("serving_resubmitted_total").inc()
        self._route(row, timeout_ms, fut, tried)

    # -- failure handling ----------------------------------------------------
    def _mark_down(self, rep: _Replica, reason: str) -> None:
        """Exclude a replica from routing and schedule its restart with
        decorrelated-jitter backoff.  Never blocks: teardown of the old
        batcher happens on the supervision thread."""
        with self._lock:
            if rep.state != "healthy":
                return
            rep.state = "down"
            rep.down_reason = reason
            rep.probe_failures = 0
            delay = self.restart_policy.backoff(
                rep.restart_attempt, rng=self._rng,
                previous=rep.last_delay,
            )
            rep.restart_attempt += 1
            rep.last_delay = delay
            rep.next_restart_t = self._clock() + delay
        tel = telemetry_mod.current()
        tel.gauge("serving_healthy_replicas_count").set(
            self.healthy_count
        )
        tel.event(
            "serving.replica_down",
            replica=rep.rid,
            reason=reason,
            restart_in_s=round(delay, 4),
        )

    def kill_replica(
        self, rid: int, reason: str = "scripted kill"
    ) -> _Replica:
        """Scripted crash of replica ``rid`` (bench scenarios, the
        selfcheck, tests): queued and in-flight requests on it fail
        transiently — and therefore resubmit to peers — and the replica
        takes the normal drain → backoff → restart path."""
        rep = next(r for r in self.replicas if r.rid == rid)
        kill = getattr(rep.batcher, "kill", None)
        if callable(kill):
            # Process mode: an actual SIGKILL.  The worker's death fails
            # its in-flight rows transiently via the pipe EOF, which is
            # the same resubmit-to-a-peer path the poison runtime fakes.
            kill(reason)
        else:
            rep.batcher.runtime = _DeadRuntime(reason)
        self._mark_down(rep, reason)
        return rep

    def kill_batcher(
        self, batcher, reason: str = "scripted kill"
    ) -> Optional[_Replica]:
        """:meth:`kill_replica` by batcher identity — the swapper holds
        batchers, not rids.  Killing through here (instead of
        ``batcher.kill``) marks the replica down in the same call, so
        health state never reports a converge-killed worker healthy."""
        for rep in self.replicas:
            if rep.batcher is batcher:
                return self.kill_replica(rep.rid, reason)
        # Not a current replica (already restarted past it): best-effort
        # direct kill of the orphaned batcher.
        kill = getattr(batcher, "kill", None)
        if callable(kill):
            kill(reason)
        return None

    # -- supervision thread --------------------------------------------------
    def _probe_loop(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self._tick()
            except Exception:  # noqa: BLE001 — supervision must survive
                pass

    def _tick(self) -> None:
        now = self._clock()
        for rep in list(self.replicas):
            if self._stop.is_set():
                return
            if rep.state == "down":
                # Drain off the request path: queued items flow through
                # the dispatch loop (fast-failing on a killed replica's
                # poison runtime), then the thread exits.  Idempotent.
                rep.batcher.stop(timeout=1.0)
                if now >= rep.next_restart_t:
                    self._restart(rep)
            elif rep.state == "healthy":
                self._probe(rep)

    def _probe(self, rep: _Replica) -> None:
        tel = telemetry_mod.current()
        try:
            fut = rep.batcher.submit(
                Row(features={}, ids={}), bypass_admission=True
            )
            result = fut.result(timeout=self.probe_timeout_s)
            if result is None:
                raise RuntimeError("probe returned no result")
        except Exception as exc:  # noqa: BLE001 — any failure counts
            rep.probe_failures += 1
            tel.counter("serving_probe_failures_total").inc()
            if rep.probe_failures >= self.probe_failure_threshold:
                self._mark_down(
                    rep,
                    f"{rep.probe_failures} consecutive probe failures "
                    f"(last: {exc})"[:200],
                )
            return
        rep.probe_failures = 0
        # Sustained health resets the backoff walk (a replica that
        # answers probes again is trusted again; see the flapping
        # runbook in ops/README.md for threshold tuning).
        rep.restart_attempt = 0
        rep.last_delay = None

    def _restart(self, rep: _Replica) -> None:
        tel = telemetry_mod.current()
        try:
            if self.pool is not None:
                batcher = self.pool.new_replica(
                    rep.rid, self.batcher_config, policy=self.policy
                )
                runtime = batcher.runtime
            else:
                runtime = self.runtime_factory()
                batcher = MicroBatcher(
                    runtime, self.batcher_config, policy=self.policy
                ).start()
        except Exception as exc:  # noqa: BLE001 — reschedule with backoff
            with self._lock:
                delay = self.restart_policy.backoff(
                    rep.restart_attempt, rng=self._rng,
                    previous=rep.last_delay,
                )
                rep.restart_attempt += 1
                rep.last_delay = delay
                rep.next_restart_t = self._clock() + delay
            tel.event(
                "serving.replica_restart_failed",
                replica=rep.rid,
                error=f"{type(exc).__name__}: {exc}"[:200],
                retry_in_s=round(delay, 4),
            )
            return
        # Restarted replicas come back under the LIVE quota lease, not
        # the static spec (serving/fleet.py); a failed apply heals at
        # the next lease renewal.
        with self._lock:
            overrides = dict(self._quota_overrides)
        for tenant, (rate, burst) in overrides.items():
            try:
                batcher.set_tenant_quota(
                    tenant, *self._per_replica_quota(rate, burst)
                )
            except Exception:  # noqa: BLE001 — next lease re-applies
                pass
        with self._lock:
            rep.batcher = batcher
            rep.state = "healthy"
            rep.probe_failures = 0
            rep.down_reason = None
            rep.restarts += 1
        tel.counter("serving_replica_restarts_total").inc()
        tel.gauge("serving_healthy_replicas_count").set(
            self.healthy_count
        )
        tel.event(
            "serving.replica_restarted",
            replica=rep.rid,
            restarts=rep.restarts,
            model_version=getattr(runtime, "model_version", 1),
        )

    # -- hot-swap integration ------------------------------------------------
    def swap_targets(self) -> list[MicroBatcher]:
        """The batchers a hot-swap rolls: every HEALTHY replica.  Down
        replicas rejoin on the new version via the updated factory."""
        return [r.batcher for r in self._healthy()]

    def on_swap_commit(
        self, model, index_maps, config: RuntimeConfig,
        version: int, path: Optional[str],
    ) -> None:
        """HotSwapper commit hook: restarts must come back on the
        NOW-SERVING version, so rebuild the replica factory around the
        committed model.  (A restart racing the commit window may build
        the prior version; its next swap or kill converges it.)"""
        if self.pool is not None:
            # Process mode: restarts attach the pool's CURRENT
            # generation, which the swapper already advanced via
            # commit_generation — there is no factory to rebuild.
            return

        def factory() -> ScoringRuntime:
            rt = ScoringRuntime(model, index_maps, config)
            rt.model_version = version
            rt.model_path = path
            return rt

        self.runtime_factory = factory

    def on_tenant_swap_commit(
        self, tenant: str, model, index_maps,
        config: Optional[RuntimeConfig], version: Optional[int],
        path: Optional[str],
    ) -> None:
        """HotSwapper tenant-commit hook: retain what a restart needs to
        re-apply this tenant's route on a fresh replica.  An all-None
        payload means the tenant rolled back onto the default route —
        drop the retained entry."""
        if self.pool is not None:
            # Process mode: respawned workers replay routes from the
            # pool's tenant-generation registry (procpool.py).
            return
        with self._lock:
            if model is None:
                self._tenant_factories.pop(tenant, None)
            else:
                self._tenant_factories[tenant] = (
                    model, index_maps, config, version, path
                )

    # -- observability -------------------------------------------------------
    @property
    def degraded(self) -> bool:
        return any(
            getattr(r.batcher.runtime, "degraded", False)
            for r in self._healthy()
        )

    @property
    def ready(self) -> bool:
        return self._started and any(
            getattr(r.batcher.runtime, "ready", False)
            for r in self._healthy()
        )

    def stats(self) -> dict:
        with self._lock:
            replicas = [
                {
                    "rid": r.rid,
                    "state": r.state,
                    "restarts": r.restarts,
                    "probe_failures": r.probe_failures,
                    "restart_attempt": r.restart_attempt,
                    "down_reason": r.down_reason,
                    "model_version": getattr(
                        r.batcher.runtime, "model_version", None
                    ),
                    "queue_depth": r.batcher.queue_depth,
                }
                for r in self.replicas
            ]
        return {
            "n_replicas": self.n_replicas,
            "healthy": self.healthy_count,
            "replicas": replicas,
        }
