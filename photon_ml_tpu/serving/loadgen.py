"""Built-in load generators for the serving path.

Two disciplines, both driving ``ScoringService.submit``:

- **Closed loop** (:func:`closed_loop`): N client threads, each with one
  request in flight — measures the service's achievable throughput at a
  concurrency level (latency and throughput are coupled; this is the
  classic saturation probe).
- **Open loop** (:func:`open_loop`): requests arrive on a Poisson clock
  at ``rate`` rps regardless of completions — measures latency under a
  FIXED offered load, including the queueing delay a closed loop hides
  (coordinated omission).  Arrivals that find the queue full count as
  rejections, which is the admission-control design working as intended.

On top of the two disciplines, **scripted scenarios** (:func:`run_scenario`
over the :data:`SCENARIOS` catalog) chain open-loop phases with varying
rate, entity skew, and mid-phase ACTIONS (hot-swap, replica kill) — the
repeatable "a bad day in serving" scripts that ``bench_serving`` and the
HA selfcheck replay:

- ``diurnal``      — rate ramps up 4x and back down (the daily curve);
  admission tiers should engage at the peak and release after.
- ``skew_shift``   — the hot entity set jumps to a disjoint pool
  mid-run; the LRU hot tables churn and re-converge.
- ``swap_under_load``   — a model hot-swap commits mid-phase while
  traffic flows; zero failed requests expected.
- ``replica_kill`` — a replica is killed mid-phase; the supervisor
  resubmits and restarts; zero failed requests expected.
- ``freshness``    — concept drift: the hot pool shifts (as in
  ``skew_shift``) while an online-refined delta publishes and
  hot-applies mid-phase (``freshness/``); zero failed requests expected.
- ``worker_kill``  — process-mode only: a worker PROCESS takes a real
  SIGKILL mid-phase; same zero-failed-requests contract through the
  pipe-EOF resubmission path.
- ``noisy_neighbor`` — TWO tenants: an aggressor bursts to ~10x its
  quota while a victim holds steady; the tenancy layer must shed the
  aggressor alone — victim p99 inside its SLO, zero victim failures.
  Tenant-aware: replay with :func:`run_noisy_neighbor` (per-tenant
  outcome accounting), not the tenant-blind :func:`run_scenario`.

Per-phase and whole-run p50/p99 come from the same shared
``telemetry.Histogram.quantile`` the live exposition uses.

Used by ``python -m photon_ml_tpu.serving --loadgen ...`` and by
``bench.py``'s ``bench_serving`` section.
"""

from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
import urllib.parse
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Optional

import numpy as np

from photon_ml_tpu.telemetry import Histogram
from photon_ml_tpu.serving.batcher import DeadlineExceededError, RejectedError


@dataclasses.dataclass
class LoadReport:
    """Latency/throughput summary of one load-generator run."""

    mode: str
    wall_seconds: float
    completed: int
    rejected: int
    errors: int
    latencies_ms: np.ndarray  # completed requests only, milliseconds

    @property
    def throughput_rps(self) -> float:
        return self.completed / self.wall_seconds if self.wall_seconds else 0.0

    def latency_histogram(self) -> Histogram:
        """The latencies folded into a telemetry histogram — the same
        bucket grid and quantile estimator the live /metrics exposition
        uses, so a loadgen report and a scraped
        ``serving_request_latency_seconds`` quantile are directly
        comparable (cached; build cost paid once)."""
        hist = getattr(self, "_hist", None)
        if hist is None:
            hist = Histogram(threading.Lock())
            for v in self.latencies_ms:
                hist.observe(v)
            self._hist = hist
        return hist

    def percentile_ms(self, q: float) -> Optional[float]:
        if len(self.latencies_ms) == 0:
            return None
        return float(self.latency_histogram().quantile(q / 100.0))

    def snapshot(self) -> dict:
        return {
            "mode": self.mode,
            "wall_seconds": round(self.wall_seconds, 3),
            "completed": self.completed,
            "rejected": self.rejected,
            "errors": self.errors,
            "throughput_rps": round(self.throughput_rps, 1),
            "latency_p50_ms": _round(self.percentile_ms(50)),
            "latency_p90_ms": _round(self.percentile_ms(90)),
            "latency_p99_ms": _round(self.percentile_ms(99)),
            "latency_p999_ms": _round(self.percentile_ms(99.9)),
            "latency_max_ms": _round(
                float(self.latencies_ms.max())
                if len(self.latencies_ms) else None
            ),
        }


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 3)


def closed_loop(
    submit: Callable,
    make_request: Callable[[int], object],
    clients: int = 8,
    duration_s: float = 5.0,
    timeout_s: float = 30.0,
) -> LoadReport:
    """``clients`` threads, one in-flight request each, for
    ``duration_s``.  ``make_request(i)`` builds the i-th request (vary it
    so the hot/cold split sees a realistic entity stream)."""
    latencies: list[list[float]] = [[] for _ in range(clients)]
    counts = np.zeros((clients, 3), np.int64)  # completed/rejected/errors
    stop = time.perf_counter() + duration_s
    seq = [0]
    seq_lock = threading.Lock()

    def client(ci: int) -> None:
        while time.perf_counter() < stop:
            with seq_lock:
                i = seq[0]
                seq[0] += 1
            t0 = time.perf_counter()
            try:
                fut = submit(make_request(i))
                fut.result(timeout=timeout_s)
            except RejectedError:
                counts[ci, 1] += 1
                continue
            except Exception:  # noqa: BLE001 — loadgen counts, not raises
                counts[ci, 2] += 1
                continue
            latencies[ci].append((time.perf_counter() - t0) * 1e3)
            counts[ci, 0] += 1

    t_start = time.perf_counter()
    threads = [
        threading.Thread(target=client, args=(ci,), daemon=True)
        for ci in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return LoadReport(
        mode=f"closed(clients={clients})",
        wall_seconds=wall,
        completed=int(counts[:, 0].sum()),
        rejected=int(counts[:, 1].sum()),
        errors=int(counts[:, 2].sum()),
        latencies_ms=np.concatenate(
            [np.asarray(c) for c in latencies]
        ) if any(latencies) else np.zeros(0),
    )


def open_loop(
    submit: Callable,
    make_request: Callable[[int], object],
    rate_rps: float = 200.0,
    duration_s: float = 5.0,
    timeout_s: float = 30.0,
    seed: int = 0,
) -> LoadReport:
    """Poisson arrivals at ``rate_rps`` for ``duration_s``; latency is
    measured from the SCHEDULED arrival time (no coordinated omission —
    a stalled service accrues queueing delay against every later
    arrival)."""
    rng = np.random.default_rng(seed)
    results_lock = threading.Lock()
    latencies: list[float] = []
    counts = [0, 0, 0]  # completed / rejected / errors
    pending: list[threading.Thread] = []

    def waiter(fut, t_sched: float) -> None:
        try:
            fut.result(timeout=timeout_s)
        except Exception:  # noqa: BLE001
            with results_lock:
                counts[2] += 1
            return
        lat = (time.perf_counter() - t_sched) * 1e3
        with results_lock:
            latencies.append(lat)
            counts[0] += 1

    t_start = time.perf_counter()
    t_next = t_start
    i = 0
    while t_next < t_start + duration_s:
        now = time.perf_counter()
        if now < t_next:
            time.sleep(t_next - now)
        try:
            fut = submit(make_request(i))
        except RejectedError:
            with results_lock:
                counts[1] += 1
        except Exception:  # noqa: BLE001
            with results_lock:
                counts[2] += 1
        else:
            t = threading.Thread(
                target=waiter, args=(fut, t_next), daemon=True
            )
            t.start()
            pending.append(t)
        i += 1
        t_next += float(rng.exponential(1.0 / rate_rps))
    for t in pending:
        t.join(timeout=timeout_s)
    wall = time.perf_counter() - t_start
    return LoadReport(
        mode=f"open(rate={rate_rps:g}rps)",
        wall_seconds=wall,
        completed=counts[0],
        rejected=counts[1],
        errors=counts[2],
        latencies_ms=np.asarray(latencies),
    )


# ---------------------------------------------------------------------------
# Scripted scenarios
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ScenarioPhase:
    """One open-loop segment of a scenario."""

    name: str
    duration_s: float
    #: offered load = ``base_rate_rps * rate_multiplier``.
    rate_multiplier: float = 1.0
    #: fraction range ``(lo, hi)`` of the entity space this phase draws
    #: from; the caller's ``make_request(i, phase)`` interprets it (a
    #: disjoint range across phases is the hot-set skew shift).
    entity_pool: Optional[tuple[float, float]] = None
    #: action fired DURING the phase (``"swap"`` / ``"kill_replica"`` /
    #: any key the caller wires), resolved via ``run_scenario(actions=)``.
    action: Optional[str] = None
    #: when within the phase the action fires (fraction of duration) —
    #: far enough in that traffic is flowing, far enough from the end
    #: that the aftermath is measured.
    action_at_frac: float = 0.25


@dataclasses.dataclass
class Scenario:
    name: str
    description: str
    phases: list


@dataclasses.dataclass
class ScenarioReport:
    """Per-phase + whole-run summary of one scenario replay."""

    scenario: str
    phases: list  # (phase_name, LoadReport) pairs
    actions: dict  # action name -> result (or error string)

    @property
    def completed(self) -> int:
        return sum(r.completed for _, r in self.phases)

    @property
    def rejected(self) -> int:
        return sum(r.rejected for _, r in self.phases)

    @property
    def errors(self) -> int:
        return sum(r.errors for _, r in self.phases)

    def percentile_ms(self, q: float) -> Optional[float]:
        latencies = [
            r.latencies_ms for _, r in self.phases if len(r.latencies_ms)
        ]
        if not latencies:
            return None
        merged = LoadReport(
            mode="merged", wall_seconds=0.0, completed=self.completed,
            rejected=self.rejected, errors=self.errors,
            latencies_ms=np.concatenate(latencies),
        )
        return merged.percentile_ms(q)

    def snapshot(self) -> dict:
        return {
            "scenario": self.scenario,
            "completed": self.completed,
            "rejected": self.rejected,
            "errors": self.errors,
            "latency_p50_ms": _round(self.percentile_ms(50)),
            "latency_p99_ms": _round(self.percentile_ms(99)),
            "latency_p999_ms": _round(self.percentile_ms(99.9)),
            "actions": self.actions,
            "phases": {
                name: report.snapshot() for name, report in self.phases
            },
        }


#: The scenario catalog ``bench_serving`` iterates.  Durations are short
#: (seconds) — these are repeatable scripts, not endurance runs; scale
#: offered load through ``base_rate_rps``.
SCENARIOS = {
    "diurnal": Scenario(
        "diurnal",
        "rate ramps 0.5x -> 2x -> 0.5x, the compressed daily curve",
        [
            ScenarioPhase("night", 1.0, rate_multiplier=0.5),
            ScenarioPhase("morning", 1.0, rate_multiplier=1.0),
            ScenarioPhase("peak", 1.0, rate_multiplier=2.0),
            ScenarioPhase("evening", 1.0, rate_multiplier=0.5),
        ],
    ),
    "skew_shift": Scenario(
        "skew_shift",
        "hot entity set jumps to a disjoint pool mid-run (LRU churn)",
        [
            ScenarioPhase("pool_a", 1.5, entity_pool=(0.0, 0.3)),
            ScenarioPhase("pool_b", 1.5, entity_pool=(0.7, 1.0)),
        ],
    ),
    "swap_under_load": Scenario(
        "swap_under_load",
        "model hot-swap commits while traffic flows; zero errors expected",
        [
            ScenarioPhase("warm", 1.0),
            ScenarioPhase("swap", 2.0, action="swap"),
            ScenarioPhase("after", 1.0),
        ],
    ),
    "replica_kill": Scenario(
        "replica_kill",
        "a replica dies mid-phase; resubmission + restart, zero errors "
        "expected",
        [
            ScenarioPhase("warm", 1.0),
            ScenarioPhase("kill", 2.0, action="kill_replica"),
            ScenarioPhase("after", 1.0),
        ],
    ),
    "freshness": Scenario(
        "freshness",
        "concept drift: the hot entity pool shifts mid-run while an "
        "online-refined delta publishes and hot-applies under load; "
        "zero errors expected (skew_shift + the freshness loop)",
        [
            ScenarioPhase("pool_a", 1.0, entity_pool=(0.0, 0.3)),
            ScenarioPhase(
                "drift", 1.5, entity_pool=(0.7, 1.0),
                action="publish_delta", action_at_frac=0.3,
            ),
            ScenarioPhase(
                "apply", 1.5, entity_pool=(0.7, 1.0),
                action="apply_delta", action_at_frac=0.25,
            ),
        ],
    ),
    "worker_kill": Scenario(
        "worker_kill",
        "a worker PROCESS is SIGKILLed mid-phase (process-mode serving); "
        "pipe EOF -> resubmission -> respawn, zero errors expected",
        [
            ScenarioPhase("warm", 1.0),
            ScenarioPhase("kill", 2.0, action="kill_worker"),
            ScenarioPhase("after", 1.0),
        ],
    ),
    "noisy_neighbor": Scenario(
        "noisy_neighbor",
        "an aggressor tenant bursts to rate_multiplier x its baseline "
        "(sized ~10x its quota) while a victim tenant holds steady; the "
        "aggressor sheds alone, the victim's p99 stays inside its SLO "
        "with zero failures.  Tenant-aware: the multiplier scales the "
        "AGGRESSOR only — replay via run_noisy_neighbor, never the "
        "tenant-blind run_scenario",
        [
            ScenarioPhase("baseline", 1.0),
            ScenarioPhase("burst", 2.0, rate_multiplier=10.0),
            ScenarioPhase("recovery", 1.0),
        ],
    ),
    "host_kill": Scenario(
        "host_kill",
        "a whole serving HOST dies mid-phase behind the FleetRouter "
        "(listener torn down abruptly; serving/fleet.py) and comes "
        "back later; the router marks it down, resubmits in-flight "
        "requests to peers, and re-admits it via reconnect probes — "
        "zero failed requests expected, the ReplicaSupervisor's gate "
        "one tier up",
        [
            ScenarioPhase("warm", 1.0),
            ScenarioPhase("kill", 2.0, action="kill_host"),
            ScenarioPhase(
                "recover", 1.0,
                action="restart_host", action_at_frac=0.1,
            ),
        ],
    ),
    "host_join_drain": Scenario(
        "host_join_drain",
        "fleet membership churns under load (cluster/membership.py): a "
        "cold host registers mid-phase and the MembershipWatcher joins "
        "it into the FleetRouter once its ready probe passes; later a "
        "veteran host drains — in-flight requests finish, new traffic "
        "re-spreads, the aggregator stops summing the departed host.  "
        "Zero failed requests expected through both transitions",
        [
            ScenarioPhase("warm", 1.0),
            ScenarioPhase("join", 1.5, action="join_host"),
            ScenarioPhase("drain", 1.5, action="drain_host"),
        ],
    ),
    "coordinator_failover": Scenario(
        "coordinator_failover",
        "the leader quota-coordinator replica is killed mid-phase "
        "(cluster/coordination.py): hosts ride the degrade-to-last-"
        "lease contract until a follower's leader lease claim wins, "
        "replays the grant journal, and resumes exact enforcement — "
        "failover within one lease TTL, over-admission bounded to one "
        "lease window, zero failed requests throughout",
        [
            ScenarioPhase("baseline", 1.5),
            ScenarioPhase("kill", 2.0, action="kill_coordinator"),
            ScenarioPhase(
                "recover", 1.5,
                action="restart_coordinator", action_at_frac=0.1,
            ),
        ],
    ),
    "quota_partition": Scenario(
        "quota_partition",
        "every host's LeaseClient loses its path to the "
        "QuotaCoordinator mid-phase (serving/fleet.py): hosts degrade "
        "to their LAST lease — never unlimited, never zero — so "
        "fleet-wide admission stays within one lease window of the "
        "budget; after heal, exact enforcement resumes.  Zero "
        "non-shed errors expected throughout",
        [
            ScenarioPhase("baseline", 1.5),
            ScenarioPhase("partition", 2.0, action="partition"),
            ScenarioPhase("heal", 1.5, action="heal"),
        ],
    ),
}


def run_scenario(
    submit: Callable,
    make_request: Callable,
    scenario: Scenario,
    base_rate_rps: float = 100.0,
    actions: Optional[dict] = None,
    timeout_s: float = 30.0,
    seed: int = 0,
) -> ScenarioReport:
    """Replay ``scenario`` phase by phase against ``submit``.

    ``make_request(i, phase)`` builds the i-th request of a phase (use
    ``phase.entity_pool`` for skew).  ``actions`` maps an action name to
    a zero-arg callable; a phase's action fires on a helper thread
    ``action_at_frac`` into the phase, so the load keeps flowing while
    the swap/kill happens — that concurrency is the whole point.  An
    action named by a phase but not wired raises ValueError up front
    (silently skipping it would report a scenario that never ran)."""
    actions = actions or {}
    for phase in scenario.phases:
        if phase.action is not None and phase.action not in actions:
            raise ValueError(
                f"scenario {scenario.name!r} phase {phase.name!r} needs "
                f"action {phase.action!r}; wire it via run_scenario("
                "actions={...})"
            )
    phase_reports: list = []
    action_results: dict = {}
    for pi, phase in enumerate(scenario.phases):
        action_thread = None
        if phase.action is not None:
            fn = actions[phase.action]
            delay = phase.duration_s * phase.action_at_frac

            def fire(fn=fn, delay=delay, key=phase.action):
                time.sleep(delay)
                try:
                    action_results[key] = fn()
                except Exception as exc:  # noqa: BLE001 — report, not crash
                    action_results[key] = (
                        f"ERROR {type(exc).__name__}: {exc}"
                    )

            action_thread = threading.Thread(
                target=fire, name=f"scenario-{phase.action}", daemon=True
            )
            action_thread.start()
        report = open_loop(
            submit,
            lambda i, phase=phase: make_request(i, phase),
            rate_rps=base_rate_rps * phase.rate_multiplier,
            duration_s=phase.duration_s,
            timeout_s=timeout_s,
            seed=seed + pi,
        )
        if action_thread is not None:
            action_thread.join(timeout=timeout_s)
        phase_reports.append((phase.name, report))
    return ScenarioReport(
        scenario=scenario.name,
        phases=phase_reports,
        actions=action_results,
    )


# ---------------------------------------------------------------------------
# Tenant-aware replay (the noisy_neighbor isolation proof)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class TenantLoadReport:
    """One tenant's outcomes across a tenant-aware replay.

    ``shed`` counts admission-control verdicts (RejectedError — quota,
    bulkhead, tier, or breaker — whether raised at submit or delivered
    through the future, which is how process-mode rejections arrive);
    ``failed`` is everything else that isn't a completion.  The victim
    gate reads ``failed`` — a shed aggressor is the design working,
    a failed victim is the isolation story broken."""

    tenant: str
    completed: int
    shed: int
    failed: int
    latencies_ms: np.ndarray

    def percentile_ms(self, q: float) -> Optional[float]:
        if len(self.latencies_ms) == 0:
            return None
        hist = Histogram(threading.Lock())
        for v in self.latencies_ms:
            hist.observe(v)
        return float(hist.quantile(q / 100.0))

    def snapshot(self) -> dict:
        return {
            "tenant": self.tenant,
            "completed": self.completed,
            "shed": self.shed,
            "failed": self.failed,
            "latency_p50_ms": _round(self.percentile_ms(50)),
            "latency_p99_ms": _round(self.percentile_ms(99)),
        }


@dataclasses.dataclass
class NoisyNeighborReport:
    """Victim/aggressor outcomes of one noisy-neighbor replay."""

    scenario: str
    victim: TenantLoadReport
    aggressor: TenantLoadReport

    def isolation(self, victim_slo_ms: float) -> dict:
        """The containment gate: victim completed traffic with ZERO
        failures and a p99 inside its SLO, while the aggressor actually
        got shed (no sheds = the burst never pressured the quota and
        the run proved nothing)."""
        p99 = self.victim.percentile_ms(99)
        ok = (
            self.victim.failed == 0
            and self.victim.completed > 0
            and p99 is not None
            and p99 <= victim_slo_ms
            and self.aggressor.shed > 0
        )
        return {
            "pass": bool(ok),
            "victim_completed": self.victim.completed,
            "victim_failed": self.victim.failed,
            "victim_p99_ms": _round(p99),
            "victim_slo_ms": victim_slo_ms,
            "aggressor_completed": self.aggressor.completed,
            "aggressor_shed": self.aggressor.shed,
            "aggressor_failed": self.aggressor.failed,
        }

    def snapshot(self) -> dict:
        return {
            "scenario": self.scenario,
            "victim": self.victim.snapshot(),
            "aggressor": self.aggressor.snapshot(),
        }


class _TenantAcct:
    """Thread-safe per-tenant outcome accumulator."""

    def __init__(self, tenant: str):
        self.tenant = tenant
        self.lock = threading.Lock()
        self.completed = 0
        self.shed = 0
        self.failed = 0
        self.latencies: list = []

    def report(self) -> TenantLoadReport:
        with self.lock:
            return TenantLoadReport(
                tenant=self.tenant,
                completed=self.completed,
                shed=self.shed,
                failed=self.failed,
                latencies_ms=np.asarray(self.latencies),
            )


def _tenant_open_loop(
    submit: Callable,
    make_request: Callable,
    phase: ScenarioPhase,
    tenant: str,
    rate_rps: float,
    acct: _TenantAcct,
    timeout_s: float,
    seed: int,
) -> None:
    """One tenant's Poisson arrival stream for one phase, classifying
    every outcome into ``acct`` (sync or via the future — process-mode
    rejections arrive as future exceptions)."""
    rng = np.random.default_rng(seed)
    pending: list = []

    def waiter(fut, t_sched: float) -> None:
        try:
            fut.result(timeout=timeout_s)
        except RejectedError:
            with acct.lock:
                acct.shed += 1
            return
        except Exception:  # noqa: BLE001 — loadgen counts, not raises
            with acct.lock:
                acct.failed += 1
            return
        lat = (time.perf_counter() - t_sched) * 1e3
        with acct.lock:
            acct.latencies.append(lat)
            acct.completed += 1

    t_start = time.perf_counter()
    t_next = t_start
    i = 0
    while t_next < t_start + phase.duration_s:
        now = time.perf_counter()
        if now < t_next:
            time.sleep(t_next - now)
        try:
            fut = submit(make_request(i, phase, tenant))
        except RejectedError:
            with acct.lock:
                acct.shed += 1
        except Exception:  # noqa: BLE001
            with acct.lock:
                acct.failed += 1
        else:
            t = threading.Thread(
                target=waiter, args=(fut, t_next), daemon=True
            )
            t.start()
            pending.append(t)
        i += 1
        t_next += float(rng.exponential(1.0 / rate_rps))
    for t in pending:
        t.join(timeout=timeout_s)


def run_noisy_neighbor(
    submit: Callable,
    make_request: Callable,
    victim: str = "victim",
    aggressor: str = "aggressor",
    victim_rate_rps: float = 40.0,
    aggressor_rate_rps: float = 40.0,
    scenario: Optional[Scenario] = None,
    timeout_s: float = 30.0,
    seed: int = 0,
) -> NoisyNeighborReport:
    """Replay the noisy-neighbor script: per phase, the victim offers
    ``victim_rate_rps`` and the aggressor offers ``aggressor_rate_rps *
    phase.rate_multiplier`` — the multiplier scales the AGGRESSOR only,
    so the burst phase is the aggressor alone going over quota while the
    victim's offered load never changes.  ``make_request(i, phase,
    tenant)`` must build a request carrying the tenant id.  Outcomes are
    classified per tenant (RejectedError = shed, any other
    non-completion = failed); gate the result with
    :meth:`NoisyNeighborReport.isolation`."""
    scenario = scenario or SCENARIOS["noisy_neighbor"]
    accts = {victim: _TenantAcct(victim), aggressor: _TenantAcct(aggressor)}
    for pi, phase in enumerate(scenario.phases):
        streams = [
            (victim, victim_rate_rps),
            (aggressor, aggressor_rate_rps * phase.rate_multiplier),
        ]
        threads = [
            threading.Thread(
                target=_tenant_open_loop,
                args=(
                    submit, make_request, phase, tenant, rate,
                    accts[tenant], timeout_s, seed + 7 * pi + ti,
                ),
                name=f"noisy-{phase.name}-{tenant}",
                daemon=True,
            )
            for ti, (tenant, rate) in enumerate(streams)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    return NoisyNeighborReport(
        scenario=scenario.name,
        victim=accts[victim].report(),
        aggressor=accts[aggressor].report(),
    )


# ---------------------------------------------------------------------------
# Fleet-aware replay (host_kill / quota_partition, serving/fleet.py)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class FleetScenarioReport:
    """Per-phase shed/failed-classified outcomes of a fleet replay.

    The host_kill gate reads the whole-run ``failed`` and ``shed``
    (both must be zero for an in-quota tenant: a dying host may delay
    a request, never fail or reject it); the quota_partition gate reads
    PER-PHASE ``completed`` against budget × phase duration (admitted
    rate within one lease window of the budget while partitioned,
    exact enforcement after heal) with ``failed == 0`` throughout —
    sheds there are the design working."""

    scenario: str
    tenant: str
    phases: list  # (phase_name, duration_s, offered_rps, TenantLoadReport)
    actions: dict  # action name -> result (or error string)

    @property
    def completed(self) -> int:
        return sum(r.completed for _, _, _, r in self.phases)

    @property
    def shed(self) -> int:
        return sum(r.shed for _, _, _, r in self.phases)

    @property
    def failed(self) -> int:
        return sum(r.failed for _, _, _, r in self.phases)

    def phase(self, name: str) -> TenantLoadReport:
        for pname, _, _, report in self.phases:
            if pname == name:
                return report
        raise KeyError(f"no phase {name!r} in {self.scenario}")

    def snapshot(self) -> dict:
        return {
            "scenario": self.scenario,
            "tenant": self.tenant,
            "completed": self.completed,
            "shed": self.shed,
            "failed": self.failed,
            "actions": self.actions,
            "phases": {
                name: dict(
                    report.snapshot(),
                    duration_s=_round(duration),
                    offered_rps=_round(offered),
                )
                for name, duration, offered, report in self.phases
            },
        }


def run_fleet_scenario(
    submit: Callable,
    make_request: Callable,
    scenario: Scenario,
    tenant: str = "acme",
    base_rate_rps: float = 120.0,
    actions: Optional[dict] = None,
    timeout_s: float = 30.0,
    seed: int = 0,
) -> FleetScenarioReport:
    """Replay a fleet scenario (host_kill / quota_partition) as ONE
    tenant's open-loop stream with shed/failed-classified outcomes.

    Same action contract as :func:`run_scenario` (unwired actions raise
    up front; actions fire on a helper thread mid-phase), but outcomes
    are accounted per phase through :class:`TenantLoadReport` so the
    gates can tell admission-control sheds (RejectedError, through the
    future or at submit) from real failures.  ``make_request(i, phase,
    tenant)`` must build a wire request carrying the tenant id."""
    actions = actions or {}
    for phase in scenario.phases:
        if phase.action is not None and phase.action not in actions:
            raise ValueError(
                f"scenario {scenario.name!r} phase {phase.name!r} needs "
                f"action {phase.action!r}; wire it via "
                "run_fleet_scenario(actions={...})"
            )
    phase_rows: list = []
    action_results: dict = {}
    for pi, phase in enumerate(scenario.phases):
        action_thread = None
        if phase.action is not None:
            fn = actions[phase.action]
            delay = phase.duration_s * phase.action_at_frac

            def fire(fn=fn, delay=delay, key=phase.action):
                time.sleep(delay)
                try:
                    action_results[key] = fn()
                except Exception as exc:  # noqa: BLE001 — report
                    action_results[key] = (
                        f"ERROR {type(exc).__name__}: {exc}"
                    )

            action_thread = threading.Thread(
                target=fire, name=f"fleet-{phase.action}", daemon=True
            )
            action_thread.start()
        acct = _TenantAcct(tenant)
        rate = base_rate_rps * phase.rate_multiplier
        _tenant_open_loop(
            submit, make_request, phase, tenant, rate, acct,
            timeout_s, seed + pi,
        )
        if action_thread is not None:
            action_thread.join(timeout=timeout_s)
        phase_rows.append(
            (phase.name, phase.duration_s, rate, acct.report())
        )
    return FleetScenarioReport(
        scenario=scenario.name,
        tenant=tenant,
        phases=phase_rows,
        actions=action_results,
    )


# ---------------------------------------------------------------------------
# HTTP submitter (wire A/B benchmarking)
# ---------------------------------------------------------------------------

class HttpSubmitter:
    """A ``submit(request) -> Future`` adapter that drives POST /score
    over HTTP with PERSISTENT connections — one keep-alive
    ``http.client.HTTPConnection`` per worker thread, so the measured
    numbers are the data plane (framing + parse + score), not TCP
    handshakes.

    ``wire_format="json"`` sends the JSON compatibility body;
    ``"binary"`` sends a serving/wire.py request frame and decodes the
    frame response — the A/B lever ``bench.py``'s
    ``_bench_serving_wire`` pulls.  Per-row errors come back as the
    same exceptions the in-process ``ScoringService.submit`` path
    raises (RejectedError / DeadlineExceededError), so the load
    generators count rejections identically either way.
    """

    def __init__(
        self,
        base_url: str,
        wire_format: str = "json",
        workers: int = 16,
        timeout_s: float = 30.0,
    ):
        if wire_format not in ("json", "binary"):
            raise ValueError(
                f"wire_format must be 'json' or 'binary', got "
                f"{wire_format!r}"
            )
        parsed = urllib.parse.urlparse(base_url)
        if not parsed.hostname:
            raise ValueError(f"base_url {base_url!r} has no host")
        self._host = parsed.hostname
        self._port = parsed.port or 80
        self.wire_format = wire_format
        self._timeout_s = timeout_s
        self._local = threading.local()
        self._pool = ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="http-loadgen"
        )

    # -- per-thread connection ---------------------------------------------
    def _conn(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(
                self._host, self._port, timeout=self._timeout_s
            )
            self._local.conn = conn
        return conn

    def _reset_conn(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
        self._local.conn = None

    # -- one round-trip -----------------------------------------------------
    def _encode(self, request: dict) -> tuple:
        if self.wire_format == "binary":
            from photon_ml_tpu.serving import wire

            return wire.encode_request([request]), wire.CONTENT_TYPE
        return (
            json.dumps({"rows": [request]}).encode(), "application/json"
        )

    def _call(self, request: dict) -> dict:
        body, ctype = self._encode(request)
        for attempt in (0, 1):
            conn = self._conn()
            try:
                conn.request("POST", "/score", body=body, headers={
                    "Content-Type": ctype,
                    "Content-Length": str(len(body)),
                })
                resp = conn.getresponse()
                raw = resp.read()
                break
            except (http.client.HTTPException, OSError):
                # A dropped keep-alive connection: reconnect once.
                self._reset_conn()
                if attempt:
                    raise
        resp_ctype = (resp.getheader("Content-Type") or "").split(";")[0]
        if resp_ctype == "application/x-photon-frame":
            from photon_ml_tpu.serving import wire

            result = wire.decode_response(raw)[0]
        else:
            payload = json.loads(raw or b"{}")
            results = payload.get("results")
            if not results:
                raise RuntimeError(
                    payload.get("error") or f"HTTP {resp.status}"
                )
            result = results[0]
        if "error" in result:
            kind = result.get("kind")
            if kind == "rejected":
                raise RejectedError(result["error"])
            if kind == "deadline":
                raise DeadlineExceededError(result["error"])
            raise RuntimeError(result["error"])
        return result

    # -- loadgen surface ----------------------------------------------------
    def submit(self, request: dict):
        """Enqueue one request; returns a Future resolving to the
        result dict (or raising like ``ScoringService.submit``'s
        future)."""
        return self._pool.submit(self._call, request)

    def close(self) -> None:
        self._pool.shutdown(wait=True)

    def __enter__(self) -> "HttpSubmitter":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
