"""Built-in load generators for the serving path.

Two disciplines, both driving ``ScoringService.submit``:

- **Closed loop** (:func:`closed_loop`): N client threads, each with one
  request in flight — measures the service's achievable throughput at a
  concurrency level (latency and throughput are coupled; this is the
  classic saturation probe).
- **Open loop** (:func:`open_loop`): requests arrive on a Poisson clock
  at ``rate`` rps regardless of completions — measures latency under a
  FIXED offered load, including the queueing delay a closed loop hides
  (coordinated omission).  Arrivals that find the queue full count as
  rejections, which is the admission-control design working as intended.

Used by ``python -m photon_ml_tpu.serving --loadgen ...`` and by
``bench.py``'s ``bench_serving`` section.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Callable, Optional

import numpy as np

from photon_ml_tpu.telemetry import Histogram
from photon_ml_tpu.serving.batcher import RejectedError


@dataclasses.dataclass
class LoadReport:
    """Latency/throughput summary of one load-generator run."""

    mode: str
    wall_seconds: float
    completed: int
    rejected: int
    errors: int
    latencies_ms: np.ndarray  # completed requests only, milliseconds

    @property
    def throughput_rps(self) -> float:
        return self.completed / self.wall_seconds if self.wall_seconds else 0.0

    def latency_histogram(self) -> Histogram:
        """The latencies folded into a telemetry histogram — the same
        bucket grid and quantile estimator the live /metrics exposition
        uses, so a loadgen report and a scraped
        ``serving_request_latency_seconds`` quantile are directly
        comparable (cached; build cost paid once)."""
        hist = getattr(self, "_hist", None)
        if hist is None:
            hist = Histogram(threading.Lock())
            for v in self.latencies_ms:
                hist.observe(v)
            self._hist = hist
        return hist

    def percentile_ms(self, q: float) -> Optional[float]:
        if len(self.latencies_ms) == 0:
            return None
        return float(self.latency_histogram().quantile(q / 100.0))

    def snapshot(self) -> dict:
        return {
            "mode": self.mode,
            "wall_seconds": round(self.wall_seconds, 3),
            "completed": self.completed,
            "rejected": self.rejected,
            "errors": self.errors,
            "throughput_rps": round(self.throughput_rps, 1),
            "latency_p50_ms": _round(self.percentile_ms(50)),
            "latency_p90_ms": _round(self.percentile_ms(90)),
            "latency_p99_ms": _round(self.percentile_ms(99)),
            "latency_max_ms": _round(
                float(self.latencies_ms.max())
                if len(self.latencies_ms) else None
            ),
        }


def _round(v: Optional[float]) -> Optional[float]:
    return None if v is None else round(v, 3)


def closed_loop(
    submit: Callable,
    make_request: Callable[[int], object],
    clients: int = 8,
    duration_s: float = 5.0,
    timeout_s: float = 30.0,
) -> LoadReport:
    """``clients`` threads, one in-flight request each, for
    ``duration_s``.  ``make_request(i)`` builds the i-th request (vary it
    so the hot/cold split sees a realistic entity stream)."""
    latencies: list[list[float]] = [[] for _ in range(clients)]
    counts = np.zeros((clients, 3), np.int64)  # completed/rejected/errors
    stop = time.perf_counter() + duration_s
    seq = [0]
    seq_lock = threading.Lock()

    def client(ci: int) -> None:
        while time.perf_counter() < stop:
            with seq_lock:
                i = seq[0]
                seq[0] += 1
            t0 = time.perf_counter()
            try:
                fut = submit(make_request(i))
                fut.result(timeout=timeout_s)
            except RejectedError:
                counts[ci, 1] += 1
                continue
            except Exception:  # noqa: BLE001 — loadgen counts, not raises
                counts[ci, 2] += 1
                continue
            latencies[ci].append((time.perf_counter() - t0) * 1e3)
            counts[ci, 0] += 1

    t_start = time.perf_counter()
    threads = [
        threading.Thread(target=client, args=(ci,), daemon=True)
        for ci in range(clients)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    return LoadReport(
        mode=f"closed(clients={clients})",
        wall_seconds=wall,
        completed=int(counts[:, 0].sum()),
        rejected=int(counts[:, 1].sum()),
        errors=int(counts[:, 2].sum()),
        latencies_ms=np.concatenate(
            [np.asarray(c) for c in latencies]
        ) if any(latencies) else np.zeros(0),
    )


def open_loop(
    submit: Callable,
    make_request: Callable[[int], object],
    rate_rps: float = 200.0,
    duration_s: float = 5.0,
    timeout_s: float = 30.0,
    seed: int = 0,
) -> LoadReport:
    """Poisson arrivals at ``rate_rps`` for ``duration_s``; latency is
    measured from the SCHEDULED arrival time (no coordinated omission —
    a stalled service accrues queueing delay against every later
    arrival)."""
    rng = np.random.default_rng(seed)
    results_lock = threading.Lock()
    latencies: list[float] = []
    counts = [0, 0, 0]  # completed / rejected / errors
    pending: list[threading.Thread] = []

    def waiter(fut, t_sched: float) -> None:
        try:
            fut.result(timeout=timeout_s)
        except Exception:  # noqa: BLE001
            with results_lock:
                counts[2] += 1
            return
        lat = (time.perf_counter() - t_sched) * 1e3
        with results_lock:
            latencies.append(lat)
            counts[0] += 1

    t_start = time.perf_counter()
    t_next = t_start
    i = 0
    while t_next < t_start + duration_s:
        now = time.perf_counter()
        if now < t_next:
            time.sleep(t_next - now)
        try:
            fut = submit(make_request(i))
        except RejectedError:
            with results_lock:
                counts[1] += 1
        except Exception:  # noqa: BLE001
            with results_lock:
                counts[2] += 1
        else:
            t = threading.Thread(
                target=waiter, args=(fut, t_next), daemon=True
            )
            t.start()
            pending.append(t)
        i += 1
        t_next += float(rng.exponential(1.0 / rate_rps))
    for t in pending:
        t.join(timeout=timeout_s)
    wall = time.perf_counter() - t_start
    return LoadReport(
        mode=f"open(rate={rate_rps:g}rps)",
        wall_seconds=wall,
        completed=counts[0],
        rejected=counts[1],
        errors=counts[2],
        latencies_ms=np.asarray(latencies),
    )
