"""The ONE implementation of GAME/GLM scoring math.

Both scoring surfaces route through this module so batch and online
results come from the same formulas:

- **Batch** (``GameTransformer`` → ``game_scoring_driver``): host compute
  over whole datasets — :func:`fixed_effect_matvec` (scipy CSR matvec),
  :func:`random_effect_block_scores` (pre-grouped block gather + einsum),
  summed into the offset column.
- **Online** (``serving.runtime.ScoringRuntime``): :func:`build_bucket_kernel`
  returns the jit'd padded-batch program — per-row multiply+reduce for
  every coordinate plus the hot-table gather — and
  :func:`dense_coefficient_rows` materializes the cold tail's per-entity
  coefficients host-side for it.

Numerical contract the online path relies on: the bucket kernel computes
each row's margin as ``offset + Σ_coord sum(x_row * w, axis=-1)`` — a
per-row reduction whose result is INDEPENDENT of the padded batch size
(XLA row reductions don't re-associate across rows), so scores are
bit-identical across the bucket ladder and between batched and
single-request scoring.  A plain matmul does NOT have this property on
CPU (verified: ``X @ w`` re-blocks by batch shape), which is why the
kernels spell the reduction out.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from photon_ml_tpu.game.model import RandomEffectModel


# ---------------------------------------------------------------------------
# Host batch path (GameTransformer / game_scoring_driver)
# ---------------------------------------------------------------------------

def fixed_effect_matvec(shard_matrix, means: np.ndarray) -> np.ndarray:
    """Fixed-effect margins of a whole scoring shard: one CSR matvec."""
    w = np.asarray(means, np.float32)
    return np.asarray(shard_matrix @ w, np.float32).ravel()


def random_effect_block_scores(
    model: RandomEffectModel, dataset
) -> np.ndarray:
    """Score a pre-grouped random-effect dataset through the block
    pipeline; entities without trained coefficients (and padding lanes)
    contribute zero.  ``dataset`` is a host-side RandomEffectDataset."""
    n = dataset.n_global_rows
    out = np.zeros(n + 1, np.float32)
    for block, block_ids in zip(dataset.blocks, dataset.entity_ids):
        coefs = model.coefficient_matrix_for(block.col_map, block_ids)
        scores = np.einsum("erd,ed->er", block.X, coefs)
        np.add.at(out, block.row_index.ravel(), scores.ravel())
    return out[:n]


def sum_margins(
    n_rows: int,
    offset: Optional[np.ndarray],
    parts: Sequence[np.ndarray],
) -> np.ndarray:
    """Offset + per-coordinate margin sum (the GAME score definition)."""
    total = (
        np.zeros(n_rows, np.float32)
        if offset is None
        else np.asarray(offset, np.float32).copy()
    )
    for p in parts:
        total += p
    return total


# ---------------------------------------------------------------------------
# Shared gather: sparse per-entity table -> dense coefficient rows
# ---------------------------------------------------------------------------

def dense_coefficient_rows(
    model: RandomEffectModel, entity_ids: Sequence
) -> np.ndarray:
    """Materialize ``(B, n_features)`` dense coefficient rows from the
    entity→(cols, vals) table — the host-side gather behind the online
    cold tail and hot-set fills.  Unknown entities (and ``None``) get the
    zero row, the same join-miss semantics as batch scoring."""
    out = np.zeros((len(entity_ids), model.n_features), np.float32)
    table = model.coefficients
    for i, key in enumerate(entity_ids):
        entry = table.get(key) if key is not None else None
        if entry is not None:
            cols, vals = entry
            out[i, cols] = vals
    return out


# ---------------------------------------------------------------------------
# Online bucket kernel (ScoringRuntime)
# ---------------------------------------------------------------------------

def build_bucket_kernel(mean_fn: Callable):
    """Jit'd padded-batch scoring program for one model structure.

    Called as ``kernel(offsets, fixed_x, fixed_w, re_x, re_tables,
    re_slots, re_cold)`` where the tuples are per-coordinate:

    - ``fixed_x[i]``: ``(B, D_i)`` dense request features,
      ``fixed_w[i]``: ``(D_i,)`` coefficients;
    - ``re_x[j]``: ``(B, D_j)`` request features,
      ``re_tables[j]``: ``(H+1, D_j)`` device-resident hot set (row 0 is
      the reserved zero row), ``re_slots[j]``: ``(B,)`` int32 hot slots
      (0 = cold/unknown/padding), ``re_cold[j]``: ``(B, D_j)`` host-side
      fallback gathers (zero on hot rows).

    ``table[slot] + cold`` is exact — one side is always the zero row —
    so a row scores bit-identically whether its entity is hot or cold.
    Returns ``(margins, means)``; one jitted callable serves every
    bucket size (jit re-specializes per shape, the runtime warms each
    bucket ahead of the request path).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(offsets, fixed_x, fixed_w, re_x, re_tables, re_slots, re_cold):
        total = offsets
        for x, w in zip(fixed_x, fixed_w):
            total = total + jnp.sum(x * w[None, :], axis=1)
        for x, table, slots, cold in zip(re_x, re_tables, re_slots, re_cold):
            coefs = table[slots] + cold
            total = total + jnp.sum(x * coefs, axis=1)
        return total, mean_fn(total)

    return kernel


def build_fused_bucket_kernel(mean_fn: Callable):
    """Single-round-trip variant of :func:`build_bucket_kernel`.

    The composed kernel takes ``1 + n_fixed + 3·n_random`` request-side
    arrays, so every batch pays that many host→device transfers plus two
    device→host readbacks.  This kernel takes exactly TWO request-side
    arguments — one packed float32 buffer and one int32 slot matrix —
    and returns margins and means STACKED into one ``(2, B)`` array, so
    a batch costs two uploads and one readback regardless of model
    structure.

    ``packed`` is ``(B, 1 + Σ fixed_dims + Σ 2·re_dims)``, laid out as
    the offset column, then each fixed coordinate's request features,
    then per random coordinate its request features followed by its
    host-gathered cold rows.  ``slots`` is ``(n_random, B)`` int32 hot
    slots.  ``fixed_w`` / ``re_tables`` are the device-resident model
    arrays, unchanged from the composed signature.

    Bit-parity contract: the margin arithmetic is the SAME expression
    sequence as the composed kernel — per-row multiply+reduce per
    coordinate, accumulated in the same order, with ``table[slot] +
    cold`` exactness — over contiguous column slices of the packed
    buffer, so fused and composed scores are bitwise identical (pinned
    by tests/test_serving_wire.py).
    """
    import jax
    import jax.numpy as jnp

    @jax.jit
    def kernel(packed, slots, fixed_w, re_tables):
        total = packed[:, 0]
        off = 1
        for w in fixed_w:
            d = w.shape[0]
            total = total + jnp.sum(
                packed[:, off:off + d] * w[None, :], axis=1
            )
            off += d
        for j, table in enumerate(re_tables):
            d = table.shape[1]
            x = packed[:, off:off + d]
            cold = packed[:, off + d:off + 2 * d]
            off += 2 * d
            coefs = table[slots[j]] + cold
            total = total + jnp.sum(x * coefs, axis=1)
        return jnp.stack([total, mean_fn(total)])

    return kernel
