from photon_ml_tpu.models.glm import (  # noqa: F401
    Coefficients,
    GeneralizedLinearModel,
    LinearRegressionModel,
    LogisticRegressionModel,
    PoissonRegressionModel,
    SmoothedHingeLossLinearSVMModel,
)
