"""GLM model classes.

The analogue of the reference's ``...ml.model`` / ``...ml.supervised``
hierarchy — ``GeneralizedLinearModel`` with ``LogisticRegressionModel``,
``LinearRegressionModel``, ``PoissonRegressionModel``,
``SmoothedHingeLossLinearSVMModel`` subclasses and a ``Coefficients``
value class carrying optional per-coefficient variances (SURVEY.md §2).

TPU-first difference: a model is a *pytree* (so it can be donated to jitted
scoring programs, vmapped over entities for random effects, and checkpointed
as flat arrays) and scoring is expressed against a
:class:`~photon_ml_tpu.data.dataset.GlmData` shard — one matvec, not a
per-row loop.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.ops import losses as losses_lib

Array = jax.Array


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["means", "variances"],
    meta_fields=[],
)
@dataclasses.dataclass
class Coefficients:
    """Coefficient vector with optional variances (the reference's
    ``Coefficients(means, variancesOption)``)."""

    means: Array  # (n_features,)
    variances: Optional[Array] = None  # (n_features,) or None

    @property
    def n_features(self) -> int:
        return self.means.shape[0]

    def norm(self, order: int | float = 2) -> Array:
        return jnp.linalg.norm(self.means, ord=order)

    @staticmethod
    def zeros(n_features: int, dtype=jnp.float32) -> "Coefficients":
        return Coefficients(means=jnp.zeros((n_features,), dtype))


@partial(
    jax.tree_util.register_dataclass,
    data_fields=["coefficients"],
    meta_fields=["task"],
)
@dataclasses.dataclass
class GeneralizedLinearModel:
    """A trained GLM: coefficients + task type.

    ``task`` selects the pointwise loss / mean function, mirroring the
    reference's per-task subclasses; the subclass constructors below are
    provided for API familiarity and return this same pytree type.
    """

    coefficients: Coefficients
    task: str  # a losses registry name: logistic | squared | poisson | smoothed_hinge

    @property
    def loss(self) -> losses_lib.PointwiseLoss:
        return losses_lib.get(self.task)

    def compute_score(self, data: GlmData) -> Array:
        """Raw margin  <w, x> + offset  per row (reference: ``computeScore``)."""
        return data.features.matvec(self.coefficients.means) + data.offsets

    def compute_mean(self, data: GlmData) -> Array:
        """Mean response via the inverse link (reference: ``computeMean`` —
        sigmoid for logistic, exp for Poisson, identity for linear/SVM)."""
        return self.loss.mean_fn(self.compute_score(data))


def LogisticRegressionModel(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coefficients, "logistic")


def LinearRegressionModel(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coefficients, "squared")


def PoissonRegressionModel(coefficients: Coefficients) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coefficients, "poisson")


def SmoothedHingeLossLinearSVMModel(
    coefficients: Coefficients,
) -> GeneralizedLinearModel:
    return GeneralizedLinearModel(coefficients, "smoothed_hinge")
