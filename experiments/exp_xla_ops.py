"""Per-op costs using bench.py's proven methodology: chain N_TIMED dependent
calls at Python level, block once at the end. This matched round-1 numbers."""
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 20
K = 32
D = 8192
NNZ = N * K
T = 30


def bench(fn, carry, args, label, work=NNZ):
    carry = jax.device_put(carry)
    out = fn(carry, *args)
    jax.block_until_ready(out)
    np.asarray(jax.tree.leaves(out)[0]).ravel()[:1]
    t0 = time.perf_counter()
    c = carry
    for _ in range(T):
        c = fn(c, *args)
    jax.block_until_ready(c)
    np.asarray(jax.tree.leaves(c)[0]).ravel()[:1]
    dt = (time.perf_counter() - t0) / T
    print(f"{label:44s} {dt*1e3:8.2f} ms  {work/dt/1e9:8.2f} Gnnz/s  "
          f"{N/dt/1e6:7.1f} Mrows/s")
    return dt


def main():
    rng = np.random.default_rng(0)
    rows_flat = np.repeat(np.arange(N, dtype=np.int32), K)
    cols_flat = rng.integers(0, D, size=NNZ, dtype=np.int32)
    vals_flat = rng.normal(size=NNZ).astype(np.float32)

    cols2d = jax.device_put(jnp.asarray(cols_flat.reshape(N, K)))
    vals2d = jax.device_put(jnp.asarray(vals_flat.reshape(N, K)))
    rows_j = jax.device_put(jnp.asarray(rows_flat))
    cols_j = jax.device_put(jnp.asarray(cols_flat))
    vals_j = jax.device_put(jnp.asarray(vals_flat))
    w0 = jnp.asarray(rng.normal(size=D).astype(np.float32))
    d0 = jnp.asarray(rng.normal(size=N).astype(np.float32))

    order = np.argsort(cols_flat, kind="stable")
    cs_rows = jax.device_put(jnp.asarray(rows_flat[order]))
    cs_cols = jax.device_put(jnp.asarray(cols_flat[order]))
    cs_vals = jax.device_put(jnp.asarray(vals_flat[order]))

    @jax.jit
    def ell_matvec(w, cols2d, vals2d):
        m = jnp.sum(vals2d * jnp.take(w, cols2d), axis=1)
        return w + 1e-20 * m[:D]

    bench(ell_matvec, w0, (cols2d, vals2d), "ELL matvec (take + row-sum)")

    @jax.jit
    def coo_matvec(w, rows_j, cols_j, vals_j):
        contrib = vals_j * jnp.take(w, cols_j)
        m = jax.ops.segment_sum(contrib, rows_j, num_segments=N,
                                indices_are_sorted=True)
        return w + 1e-20 * m[:D]

    bench(coo_matvec, w0, (rows_j, cols_j, vals_j), "COO matvec")

    @jax.jit
    def coo_rmatvec(d, rows_j, cols_j, vals_j):
        contrib = vals_j * jnp.take(d, rows_j)
        g = jax.ops.segment_sum(contrib, cols_j, num_segments=D)
        return d + 1e-20 * jnp.tile(g, N // D)

    bench(coo_rmatvec, d0, (rows_j, cols_j, vals_j), "COO rmatvec (unsorted)")

    @jax.jit
    def cs_rmatvec(d, rows, cols, vals):
        contrib = vals * jnp.take(d, rows)
        g = jax.ops.segment_sum(contrib, cols, num_segments=D,
                                indices_are_sorted=True)
        return d + 1e-20 * jnp.tile(g, N // D)

    bench(cs_rmatvec, d0, (cs_rows, cs_cols, cs_vals), "CS rmatvec (col-sorted)")

    @jax.jit
    def gather_w(w, cols2d):
        g = jnp.take(w, cols2d)
        return w + 1e-20 * jnp.sum(g[:8, :8])

    bench(gather_w, w0, (cols2d,), "gather w[cols2d] only")

    @jax.jit
    def rowsum(d, vals2d):
        m = jnp.sum(vals2d * d[:, None], axis=1)
        return d + 1e-20 * m

    bench(rowsum, d0, (vals2d,), "rowsum ref (134MB read)")

    # ELL with one-hot bf16 matmul for the gather: m = sum_k OH_k @ w
    @jax.jit
    def onehot_matvec(w, cols2d, vals2d):
        wb = w.astype(jnp.bfloat16)
        m = jnp.zeros((N,), jnp.float32)
        iota = jnp.arange(D, dtype=jnp.int32)
        for k in range(0, K, 8):
            oh = (cols2d[:, k:k+8, None] == iota).astype(jnp.bfloat16)
            mk = jax.lax.dot_general(
                oh, wb, (((2,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m = m + jnp.sum(vals2d[:, k:k+8] * mk, axis=1)
        return w + 1e-20 * m[:D]

    # (likely slow: materializes one-hot; measuring to confirm)
    # bench(onehot_matvec, w0, (cols2d, vals2d), "one-hot bf16 matvec")


if __name__ == "__main__":
    main()
