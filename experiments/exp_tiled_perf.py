"""TPU timing: tiled Pallas matrix vs COO on the bench workload, measured
honestly (fori_loop chaining inside one jit, readback-primed sync)."""
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from photon_ml_tpu.data.dataset import GlmData
from photon_ml_tpu.ops import losses
from photon_ml_tpu.ops.sparse import SparseMatrix
from photon_ml_tpu.ops.sparse_pallas import build_pallas_matrix
from photon_ml_tpu.optim.objective import GlmObjective

N, D, K = 1 << 20, 1 << 13, 32
R = 10


def measure(data, label):
    obj = GlmObjective(losses.logistic)

    @jax.jit
    def chain(w, data):
        def body(i, w):
            val, grad = obj.value_and_grad(w, data, l2_weight=1.0)
            return w - 1e-4 * grad
        return jax.lax.fori_loop(0, R, body, w)

    w = jnp.zeros(D, jnp.float32)
    out = chain(w, data)
    _ = np.asarray(out.ravel()[0:1])   # prime sync
    best = np.inf
    for i in range(3):
        wp = jnp.full((D,), np.float32(1e-3 * (i + 1)))
        _ = np.asarray(wp.ravel()[0:1])
        t0 = time.perf_counter()
        out = chain(wp, data)
        _ = np.asarray(out.ravel()[0:1])
        best = min(best, (time.perf_counter() - t0) / R)
    print(f"{label:24s} {best*1e3:8.2f} ms/eval  {N/best/1e6:8.1f} Mrows/s")
    return best


def main():
    rng = np.random.default_rng(0)
    nnz = N * K
    rows = np.repeat(np.arange(N, dtype=np.int64), K)
    cols = rng.integers(0, D, size=nnz).astype(np.int64)
    vals = rng.normal(size=nnz).astype(np.float32)
    y = (rng.uniform(size=N) < 0.5).astype(np.float32)

    t0 = time.perf_counter()
    P = build_pallas_matrix(rows, cols, vals, N, D)
    print(f"tiled layout build: {time.perf_counter()-t0:.1f}s  "
          f"depthF={P.depth_f} depthB={P.depth_b} spill={P.spill.has_spill}")
    dataP = jax.device_put(GlmData(
        features=P, labels=jnp.asarray(y),
        weights=jnp.ones(N, jnp.float32), offsets=jnp.zeros(N, jnp.float32)))
    measure(dataP, "pallas tiled")

    C = SparseMatrix(
        row_ids=jnp.asarray(rows.astype(np.int32)),
        col_ids=jnp.asarray(cols.astype(np.int32)),
        values=jnp.asarray(vals), n_rows=N, n_cols=D)
    dataC = jax.device_put(GlmData(
        features=C, labels=jnp.asarray(y),
        weights=jnp.ones(N, jnp.float32), offsets=jnp.zeros(N, jnp.float32)))
    measure(dataC, "COO XLA")


if __name__ == "__main__":
    main()
