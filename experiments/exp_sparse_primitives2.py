"""Primitive costs with dispatch latency amortized: each op runs R times
inside one jitted fori_loop with a data dependency between iterations.
All large arrays are jit ARGUMENTS (closure constants overflow the axon
remote-compile transport)."""
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 20
K = 32
D = 8192
NNZ = N * K
R = 20


def timeit_chained(step, carry0, data, reps=3):
    """step(carry, data) -> carry; jitted fori_loop of R steps."""

    @jax.jit
    def run(carry, data):
        return jax.lax.fori_loop(
            0, R, lambda i, c: step(c, data), carry)

    out = run(carry0, data)
    jax.block_until_ready(out)
    times = []
    for i in range(reps):
        # Unique carry per rep: identical invocations get cached somewhere
        # in the axon remote-execute path and return absurdly fast.
        carry = jax.block_until_ready(
            carry0 + jnp.asarray(1e-12 * (i + 1), carry0.dtype))
        t0 = time.perf_counter()
        out = run(carry, data)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return min(times) / R


def main():
    rng = np.random.default_rng(0)
    rows_flat = np.repeat(np.arange(N, dtype=np.int32), K)
    cols_flat = rng.integers(0, D, size=NNZ, dtype=np.int32)
    vals_flat = rng.normal(size=NNZ).astype(np.float32)

    cols2d = jax.device_put(jnp.asarray(cols_flat.reshape(N, K)))
    vals2d = jax.device_put(jnp.asarray(vals_flat.reshape(N, K)))
    rows_j = jax.device_put(jnp.asarray(rows_flat))
    cols_j = jax.device_put(jnp.asarray(cols_flat))
    vals_j = jax.device_put(jnp.asarray(vals_flat))
    w0 = jnp.asarray(rng.normal(size=D).astype(np.float32))
    d0 = jnp.asarray(rng.normal(size=N).astype(np.float32))

    order = np.argsort(cols_flat, kind="stable")
    cs_rows = jax.device_put(jnp.asarray(rows_flat[order]))
    cs_cols = jax.device_put(jnp.asarray(cols_flat[order]))
    cs_vals = jax.device_put(jnp.asarray(vals_flat[order]))

    results = {}

    def ell_matvec_step(w, data):
        cols2d, vals2d = data
        m = jnp.sum(vals2d * jnp.take(w, cols2d), axis=1)
        return w + 1e-20 * m[:D]

    results["ELL matvec (gather+row-sum)"] = (
        timeit_chained(ell_matvec_step, w0, (cols2d, vals2d)), NNZ)

    def coo_matvec_step(w, data):
        rows_j, cols_j, vals_j = data
        contrib = vals_j * jnp.take(w, cols_j)
        m = jax.ops.segment_sum(contrib, rows_j, num_segments=N,
                                indices_are_sorted=True)
        return w + 1e-20 * m[:D]

    results["COO matvec (sorted segsum)"] = (
        timeit_chained(coo_matvec_step, w0, (rows_j, cols_j, vals_j)), NNZ)

    def coo_rmatvec_step(d, data):
        rows_j, cols_j, vals_j = data
        contrib = vals_j * jnp.take(d, rows_j)
        g = jax.ops.segment_sum(contrib, cols_j, num_segments=D)
        return d + 1e-20 * jnp.tile(g, N // D)

    results["COO rmatvec (unsorted segsum)"] = (
        timeit_chained(coo_rmatvec_step, d0, (rows_j, cols_j, vals_j)), NNZ)

    def cs_rmatvec_step(d, data):
        cs_rows, cs_cols, cs_vals = data
        contrib = cs_vals * jnp.take(d, cs_rows)
        g = jax.ops.segment_sum(contrib, cs_cols, num_segments=D,
                                indices_are_sorted=True)
        return d + 1e-20 * jnp.tile(g, N // D)

    results["CS rmatvec (sorted segsum)"] = (
        timeit_chained(cs_rmatvec_step, d0, (cs_rows, cs_cols, cs_vals)), NNZ)

    def rowsum_step(d, data):
        (vals2d,) = data
        m = jnp.sum(vals2d * d[:, None], axis=1)
        return d + 1e-20 * m

    results["rowsum ref (read 33M f32)"] = (
        timeit_chained(rowsum_step, d0, (vals2d,)), NNZ)

    def gather_w_step(w, data):
        (cols2d,) = data
        g = jnp.take(w, cols2d)
        return w + 1e-20 * g[:256].reshape(-1)

    results["gather w only"] = (
        timeit_chained(gather_w_step, w0, (cols2d,)), NNZ)

    def gather_d_step(d, data):
        (rows_j,) = data
        g = jnp.take(d, rows_j)
        return d + 1e-20 * g[:N]

    results["gather d only (sorted idx)"] = (
        timeit_chained(gather_d_step, d0, (rows_j,)), NNZ)

    A = jax.device_put(
        jnp.asarray(rng.normal(size=(D, D)), jnp.bfloat16))

    def mm_step(B, data):
        (A,) = data
        return jnp.dot(A, B, preferred_element_type=jnp.float32).astype(
            jnp.bfloat16)

    results["bf16 8Kx8Kx8K matmul (1.1 TFLOP)"] = (
        timeit_chained(mm_step, A, (A,)), 2 * D**3)

    for name, (t, work) in results.items():
        print(f"{name:38s} {t*1e3:8.3f} ms   {work/t/1e9:9.2f} Gop/s")


if __name__ == "__main__":
    main()
