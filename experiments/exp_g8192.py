"""Microbenchmark: G8192 primitive (gather 33M values from an 8192-wide
table) implemented as a Pallas window sweep over dynamic_gather, plus the
full ELL matvec built on it.  This decides the sparse kernel design."""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1 << 20
K = 32
D = 8192
LANE = 8192
N_BLOCKS = N // LANE  # 128
W = 64  # number of 128-wide windows


def matvec_kernel(cols_ref, vals_ref, w_ref, out_ref):
    """One row-block: margins[l] = sum_k vals[k,l] * w[cols[k,l]].

    cols/vals: (K, LANE); w: (1, LANE); out: (1, LANE).
    Gather via 64-window sweep: chunk lanes in 128s, for each window t
    dynamic-gather from that 128-slice of w and select where hi == t.
    """
    def chunk_body(c, _):
        idx = cols_ref[:, pl.ds(c * 128, 128)]          # (K, 128)
        vals = vals_ref[:, pl.ds(c * 128, 128)]         # (K, 128)
        lo = idx & 127
        hi = idx >> 7

        def win_body(t, g):
            tab = jnp.broadcast_to(w_ref[0, pl.ds(t * 128, 128)], (K, 128))
            cand = jnp.take_along_axis(tab, lo, axis=1)
            return jnp.where(hi == t, cand, g)

        g = jax.lax.fori_loop(0, W, win_body, jnp.zeros((K, 128), jnp.float32))
        m = jnp.sum(vals * g, axis=0)                   # (128,)
        out_ref[0, 0, pl.ds(c * 128, 128)] = m
        return 0

    jax.lax.fori_loop(0, W, chunk_body, 0)


@jax.jit
def pallas_matvec(w, cols_T, vals_T):
    return pl.pallas_call(
        matvec_kernel,
        grid=(N_BLOCKS,),
        out_shape=jax.ShapeDtypeStruct((N_BLOCKS, 1, LANE), jnp.float32),
        in_specs=[
            pl.BlockSpec((K, LANE), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((K, LANE), lambda i: (0, i), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, LANE), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, 1, LANE), lambda i: (i, 0, 0),
                               memory_space=pltpu.VMEM),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",)),
    )(cols_T, vals_T, w.reshape(1, LANE))


def main():
    rng = np.random.default_rng(0)
    cols = rng.integers(0, D, size=(N, K), dtype=np.int32)
    vals = rng.normal(size=(N, K)).astype(np.float32)
    # Transposed ELL: (K, N); lane = row.
    cols_T = jax.device_put(jnp.asarray(cols.T.copy()))
    vals_T = jax.device_put(jnp.asarray(vals.T.copy()))
    w = jnp.asarray(rng.normal(size=D).astype(np.float32))

    # Correctness on a small slice first (block 0).
    out = pallas_matvec(w, cols_T, vals_T)
    m0 = np.asarray(out[0, 0])
    expect = (vals[:LANE] * np.asarray(w)[cols[:LANE]]).sum(1)
    err = np.abs(m0 - expect).max()
    print("correctness max err:", err)
    assert err < 1e-3

    # Timing: chain T iterations, prime with readback.
    _ = np.asarray(out.ravel()[0:1])

    @jax.jit
    def chain(w, cols_T, vals_T, reps):
        def body(i, w):
            m = pallas_matvec_inner(w, cols_T, vals_T)
            return w + 1e-20 * m[0, 0, :D]
        return jax.lax.fori_loop(0, reps, body, w)

    # inline pallas in the loop (avoid jit-in-jit weirdness)
    def pallas_matvec_inner(w, cols_T, vals_T):
        return pl.pallas_call(
            matvec_kernel,
            grid=(N_BLOCKS,),
            out_shape=jax.ShapeDtypeStruct((N_BLOCKS, 1, LANE), jnp.float32),
            in_specs=[
                pl.BlockSpec((K, LANE), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((K, LANE), lambda i: (0, i),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, LANE), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, 1, LANE), lambda i: (i, 0, 0),
                                   memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
        )(cols_T, vals_T, w.reshape(1, LANE))

    R = 10
    out = chain(w, cols_T, vals_T, R)
    _ = np.asarray(out.ravel()[0:1])
    for rep in range(2):
        wp = w + np.float32(0.001 * (rep + 1))
        _ = np.asarray(wp.ravel()[0:1])
        t0 = time.perf_counter()
        out = chain(wp, cols_T, vals_T, R)
        _ = np.asarray(out.ravel()[0:1])
        dt = (time.perf_counter() - t0) / R
        print(f"pallas ELL matvec: {dt*1e3:.2f} ms/pass  "
              f"{N/dt/1e6:.1f} Mrows/s  {N*K/dt/1e9:.2f} Gnnz/s")


if __name__ == "__main__":
    main()
