"""Chip A/B: sharded-stream per-chunk compute — tiled Pallas vs COO layout.

Times ONLY the per-chunk program on a device-resident chunk: under
shard_map each shard runs this exact local program (obj.raw_value_and_grad
on its features), so the single-chip rate IS the per-shard kernel rate;
multi-shard correctness is pinned by the CPU mesh tests.  Isolates kernel
rate from the tunnel's h2d transfer, which dominates full streamed passes
on this dev chip.

Measured 2026-07-31 (round 4): COO 0.99 M rows/s, Pallas 12.21 M rows/s per chunk -> 12.3x.
"""
import sys, time
import numpy as np
import scipy.sparse as sp

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
from photon_ml_tpu.data.streaming import make_streaming_glm_data
from photon_ml_tpu.optim.streaming import StreamingObjective

rng = np.random.default_rng(0)
n, d, nnz_row = 1 << 18, 1 << 13, 32
nnz = n * nnz_row
rows = np.repeat(np.arange(n, dtype=np.int64), nnz_row)
cols = rng.integers(0, d, size=nnz).astype(np.int64)
vals = rng.normal(size=nnz).astype(np.float32)
X = sp.coo_matrix((vals, (rows, cols)), shape=(n, d)).tocsr()
y = (rng.uniform(size=n) < 0.5).astype(np.float32)

w = jnp.zeros(d, jnp.float32)

def rate(use_pallas):
    t0 = time.perf_counter()
    s = make_streaming_glm_data(
        X, y, chunk_rows=n // 2, use_pallas=use_pallas
    )
    print(f"  build({'pallas' if use_pallas else 'coo'}): "
          f"{time.perf_counter()-t0:.1f}s, {s.n_chunks} chunks")
    from photon_ml_tpu.ops import losses
    from photon_ml_tpu.optim.objective import GlmObjective

    obj = GlmObjective(losses.logistic)
    chunk = jax.device_put(s.chunks[0])
    K = 10  # chained evals in one jit: single dispatches measure ~0.2s
            # tunnel latency, not compute (axon measurement gotcha)

    @jax.jit
    def chain(w, chunk):
        def body(i, w):
            _v, g = obj.value_and_grad(w, chunk, l2_weight=1.0)
            return w - 1e-4 * g
        return jax.lax.fori_loop(0, K, body, w)

    out = chain(w, chunk)                     # compile
    np.asarray(out.ravel()[0:1])
    best = np.inf
    for i in range(5):
        wp = jnp.full((d,), np.float32(1e-3 * (i + 1)))
        np.asarray(wp.ravel()[0:1])
        t0 = time.perf_counter()
        out = chain(wp, chunk)
        np.asarray(out.ravel()[0:1])          # true completion
        best = min(best, (time.perf_counter() - t0) / K)
    return (n // 2) / best

r_coo = rate(False)
r_pal = rate(True)
print(f"per-chunk compute: COO {r_coo/1e6:.2f} M rows/s, "
      f"Pallas {r_pal/1e6:.2f} M rows/s, speedup {r_pal/r_coo:.1f}x")
assert r_pal > 2.0 * r_coo, "streamed Pallas chunks not at kernel rate"
print("A/B OK: streamed per-chunk compute runs at the kernel rate")
