"""Matvec kernel v2: exact (col-window sublane, lane=row%128) layout.

Per 8192-row block:
- entries placed at sublane a (col-window group w = a // DEPTH), lane r%128
- ONE dynamic_gather (A, 128) with per-sublane 128-wide tables (w windows)
- row reduction: 64-step masked sweep over rowhi + within-group sublane sums
"""
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

N = 1 << 20
K = 32
D = 8192
LANE = 8192
BLOCK_ROWS = 8192
N_BLOCKS = N // BLOCK_ROWS
W = 64          # col windows of 128
DEPTH = 64      # sublane slots per (window, lane) cell
A = W * DEPTH   # 4096 sublanes per block


def build_layout(cols, vals):
    """Host layout build. cols/vals: (N, K). Returns per-block arrays
    lo (NB, A, 128) int32, v (NB, A, 128) f32, rhi (NB, A, 128) int32,
    plus spilled COO (kept tiny; asserted empty here)."""
    NB = N // BLOCK_ROWS
    lo = np.zeros((NB, A, 128), np.int32)
    v = np.zeros((NB, A, 128), np.float32)
    rhi = np.zeros((NB, A, 128), np.int32)
    n_spill = 0
    rows = np.repeat(np.arange(N, dtype=np.int64), K)
    b = rows // BLOCK_ROWS
    r_local = rows % BLOCK_ROWS
    c = cols.reshape(-1).astype(np.int64)
    win = c >> 7
    lane = r_local % 128
    # fill order: sort by (block, win, lane) then assign depth slots
    order = np.lexsort((lane, win, b))
    bs, ws, ls = b[order], win[order], lane[order]
    rh = (r_local // 128)[order]
    los = (c & 127)[order]
    vs = vals.reshape(-1)[order]
    # depth position within each (block, win, lane) cell
    key = (bs * W + ws) * 128 + ls
    uniq, start = np.unique(key, return_index=True)
    depth_pos = np.arange(len(key)) - np.repeat(start, np.diff(
        np.append(start, len(key))))
    ok = depth_pos < DEPTH
    n_spill = int((~ok).sum())
    sub = (ws * DEPTH + depth_pos)[ok]
    lo[bs[ok], sub, ls[ok]] = los[ok]
    v[bs[ok], sub, ls[ok]] = vs[ok]
    rhi[bs[ok], sub, ls[ok]] = rh[ok]
    return lo, v, rhi, n_spill


def matvec_kernel(lo_ref, v_ref, rhi_ref, wt_ref, out_ref):
    # wt_ref: (A, 128) per-sublane tables (w window for sublane's group)
    g = jnp.take_along_axis(wt_ref[:], lo_ref[0], axis=1)   # (A, 128)
    contrib = v_ref[0] * g
    rhi = rhi_ref[0]

    def h_body(h, _):
        m_h = jnp.sum(jnp.where(rhi == h, contrib, 0.0), axis=0)  # (128,)
        out_ref[0, h, :] = m_h
        return 0

    jax.lax.fori_loop(0, W, h_body, 0)


def make_matvec():
    def run(w, lo, v, rhi):
        # tables: sublane a belongs to window a // DEPTH
        w2 = w.reshape(W, 128)
        wt = jnp.repeat(w2, DEPTH, axis=0)      # (A, 128)
        return pl.pallas_call(
            matvec_kernel,
            grid=(N_BLOCKS,),
            out_shape=jax.ShapeDtypeStruct((N_BLOCKS, W, 128), jnp.float32),
            in_specs=[
                pl.BlockSpec((1, A, 128), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, A, 128), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, A, 128), lambda i: (i, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((A, 128), lambda i: (0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, W, 128), lambda i: (i, 0, 0),
                                   memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary",)),
        )(lo, v, rhi, wt)
    return run


def main():
    rng = np.random.default_rng(0)
    cols = rng.integers(0, D, size=(N, K), dtype=np.int32)
    vals = rng.normal(size=(N, K)).astype(np.float32)
    w = jnp.asarray(rng.normal(size=D).astype(np.float32))

    t0 = time.perf_counter()
    lo, v, rhi, n_spill = build_layout(cols, vals)
    print(f"layout build: {time.perf_counter()-t0:.1f}s, spill={n_spill} "
          f"({100*n_spill/(N*K):.3f}%)")

    lo_j = jax.device_put(jnp.asarray(lo))
    v_j = jax.device_put(jnp.asarray(v))
    rhi_j = jax.device_put(jnp.asarray(rhi))

    run = make_matvec()
    jrun = jax.jit(run)
    out = jrun(w, lo_j, v_j, rhi_j)
    # m[r] for r: block b=r//8192, window h=(r%8192)//128, lane r%128
    m = np.asarray(out).reshape(N_BLOCKS, W * 128).reshape(-1)
    expect = (vals[:, :] * np.asarray(w)[cols]).sum(1)
    err = np.abs(m - expect).max() if n_spill == 0 else None
    print("correctness max err:", err)

    _ = np.asarray(out.ravel()[0:1])

    @jax.jit
    def chain(w, lo, v, rhi, reps):
        def body(i, w):
            m = run(w, lo, v, rhi)
            return w + 1e-20 * m[0, :, :].reshape(-1)[:D]
        return jax.lax.fori_loop(0, reps, body, w)

    R = 10
    out2 = chain(w, lo_j, v_j, rhi_j, R)
    _ = np.asarray(out2.ravel()[0:1])
    for rep in range(2):
        wp = w + np.float32(0.001 * (rep + 1))
        _ = np.asarray(wp.ravel()[0:1])
        t0 = time.perf_counter()
        out2 = chain(wp, lo_j, v_j, rhi_j, R)
        _ = np.asarray(out2.ravel()[0:1])
        dt = (time.perf_counter() - t0) / R
        print(f"pallas matvec v2: {dt*1e3:.2f} ms/pass  "
              f"{N/dt/1e6:.1f} Mrows/s  {N*K/dt/1e9:.2f} Gnnz/s")


if __name__ == "__main__":
    main()
