"""Measure the primitive costs of the sparse value+grad hot loop on TPU.

Workload mirrors bench.py: N=1M rows, K=32 nnz/row, D=8192 features.
Times each candidate building block with min-of-k; prints a table.
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

N = 1 << 20
K = 32
D = 8192
NNZ = N * K


def timeit(fn, *args, reps=20):
    out = fn(*args)
    jax.block_until_ready(out)
    times = []
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn(*args)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    return min(times)


def main():
    rng = np.random.default_rng(0)
    rows_flat = np.repeat(np.arange(N, dtype=np.int32), K)
    cols_flat = rng.integers(0, D, size=NNZ, dtype=np.int32)
    vals_flat = rng.normal(size=NNZ).astype(np.float32)

    cols2d = jnp.asarray(cols_flat.reshape(N, K))
    vals2d = jnp.asarray(vals_flat.reshape(N, K))
    rows_j = jnp.asarray(rows_flat)
    cols_j = jnp.asarray(cols_flat)
    vals_j = jnp.asarray(vals_flat)
    w = jnp.asarray(rng.normal(size=D).astype(np.float32))
    d_vec = jnp.asarray(rng.normal(size=N).astype(np.float32))

    # Col-sorted copy for the rmatvec side.
    order = np.argsort(cols_flat, kind="stable")
    cs_rows = jnp.asarray(rows_flat[order])
    cs_cols = jnp.asarray(cols_flat[order])
    cs_vals = jnp.asarray(vals_flat[order])

    results = {}

    @jax.jit
    def gather_w(cols2d):
        return jnp.take(w, cols2d)

    results["gather w[cols2d] (33M from 8K)"] = timeit(gather_w, cols2d)

    @jax.jit
    def gather_d(rows):
        return jnp.take(d_vec, rows)

    results["gather d[rows_flat] (33M from 1M)"] = timeit(gather_d, rows_j)

    @jax.jit
    def ell_matvec(cols2d, vals2d, w):
        return jnp.sum(vals2d * jnp.take(w, cols2d), axis=1)

    results["ELL matvec (gather+reshape-sum)"] = timeit(
        ell_matvec, cols2d, vals2d, w)

    @jax.jit
    def coo_matvec(rows, cols, vals, w):
        contrib = vals * jnp.take(w, cols)
        return jax.ops.segment_sum(contrib, rows, num_segments=N,
                                   indices_are_sorted=True)

    results["COO matvec (sorted segment_sum)"] = timeit(
        coo_matvec, rows_j, cols_j, vals_j, w)

    @jax.jit
    def coo_rmatvec(rows, cols, vals, dv):
        contrib = vals * jnp.take(dv, rows)
        return jax.ops.segment_sum(contrib, cols, num_segments=D)

    results["COO rmatvec (unsorted segsum)"] = timeit(
        coo_rmatvec, rows_j, cols_j, vals_j, d_vec)

    @jax.jit
    def cs_rmatvec(rows, cols, vals, dv):
        contrib = vals * jnp.take(dv, rows)
        return jax.ops.segment_sum(contrib, cols, num_segments=D,
                                   indices_are_sorted=True)

    results["CS rmatvec (col-sorted segsum)"] = timeit(
        cs_rmatvec, cs_rows, cs_cols, cs_vals, d_vec)

    @jax.jit
    def seg_only_rows(vals):
        return jax.ops.segment_sum(vals, rows_j, num_segments=N,
                                   indices_are_sorted=True)

    results["segment_sum rows only (sorted)"] = timeit(seg_only_rows, vals_j)

    @jax.jit
    def reshape_sum(vals2d):
        return jnp.sum(vals2d, axis=1)

    results["reshape-sum rows only"] = timeit(reshape_sum, vals2d)

    for name, t in results.items():
        gnnz = NNZ / t / 1e9
        print(f"{name:42s} {t*1e3:8.3f} ms   {gnnz:8.2f} Gnnz/s "
              f"  {N/t/1e6:8.1f} Mrows/s-equiv")


if __name__ == "__main__":
    main()
