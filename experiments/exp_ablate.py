"""Ablate the tile kernel to find the dominant cost: full vs no-sweep vs
no-gather vs DMA-only.  Mirrors the window-PACKED production kernel
(photon_ml_tpu/ops/sparse_pallas.py): packed codes carry win|ohi|lo, tables
are built by masked selects over the windows.

Finding (v5e, 1M x 8192, 32 nnz/row): all modes time within ~5% — the
kernel is bandwidth-bound; table selects, gather, and output sweep fully
overlap the slot-stream DMA.
"""
import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from photon_ml_tpu.ops.sparse_pallas import (
    CODE_MASK, OBITS, TILE_C, WIN, WIN_SHIFT, WINS, build_pallas_matrix)

N, D, K = 1 << 20, 1 << 13, 32
R = 10


def make_kernel(mode, a):
    def kernel(code_ref, val_ref, tab_ref, out_ref):
        code = code_ref[0].astype(jnp.int32)
        fields = code & CODE_MASK  # empty slots carry the EMPTY sign bit
        lo = fields & (WIN - 1)
        ohi = (fields >> 7) & ((1 << OBITS) - 1)
        win = fields[:, 0:1] >> WIN_SHIFT
        v = val_ref[0]
        if mode == "dma":
            contrib = v
        else:
            def w_body(wi, acc):
                row = tab_ref[0, pl.ds(wi, 1), :]
                return jnp.where(
                    win == wi, jnp.broadcast_to(row, (a, WIN)), acc
                )

            tables = jax.lax.fori_loop(
                0, WINS, w_body, jnp.zeros((a, WIN), jnp.float32)
            )
            if mode == "nogather":
                contrib = v * tables
            else:
                g = jnp.take_along_axis(tables, lo, axis=1)
                contrib = v * g

        @pl.when(pl.program_id(1) == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        if mode in ("full", "nogather"):
            def h_body(h, _):
                part = jnp.sum(jnp.where(ohi == h, contrib, 0.0), axis=0)
                out_ref[0, pl.ds(h, 1), :] += part.reshape(1, WIN)
                return 0
            jax.lax.fori_loop(0, WINS, h_body, 0)
        else:
            out_ref[0, 0, :] += jnp.sum(contrib, axis=0)
    return kernel


def run_mode(mode, P):
    a = P.a_f
    nbo, nbg = P.nbr, P.nbc
    kern = make_kernel(mode, a)

    def apply_(code, val, vec):
        tab = vec.reshape(nbg, WINS, WIN)
        return pl.pallas_call(
            kern,
            grid=(nbo, nbg),
            out_shape=jax.ShapeDtypeStruct((nbo, WINS, WIN), jnp.float32),
            in_specs=[
                pl.BlockSpec((1, a, WIN), lambda i, j: (i * nbg + j, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, a, WIN), lambda i, j: (i * nbg + j, 0, 0),
                             memory_space=pltpu.VMEM),
                pl.BlockSpec((1, WINS, WIN), lambda i, j: (j, 0, 0),
                             memory_space=pltpu.VMEM),
            ],
            out_specs=pl.BlockSpec((1, WINS, WIN), lambda i, j: (i, 0, 0),
                                   memory_space=pltpu.VMEM),
            compiler_params=pltpu.CompilerParams(
                dimension_semantics=("arbitrary", "arbitrary")),
        )(code, val, tab)

    @jax.jit
    def chain(w, code, val):
        def body(i, w):
            m = apply_(code, val, w)
            return w + 1e-20 * m.reshape(-1)[:w.shape[0]]
        return jax.lax.fori_loop(0, R, body, w)

    w = jnp.zeros((P.nbc * TILE_C,), jnp.float32)
    code = P.f_code.reshape(P.nbr * P.nbc, a, WIN)
    val = P.f_val.reshape(P.nbr * P.nbc, a, WIN)
    out = chain(w, code, val)
    _ = np.asarray(out.ravel()[0:1])
    best = np.inf
    for i in range(2):
        wp = jnp.full_like(w, np.float32(1e-3 * (i + 1)))
        _ = np.asarray(wp.ravel()[0:1])
        t0 = time.perf_counter()
        out = chain(wp, code, val)
        _ = np.asarray(out.ravel()[0:1])
        best = min(best, (time.perf_counter() - t0) / R)
    print(f"{mode:10s} {best*1e3:8.2f} ms/pass")


def main():
    rng = np.random.default_rng(0)
    nnz = N * K
    rows = np.repeat(np.arange(N, dtype=np.int64), K)
    cols = rng.integers(0, D, size=nnz).astype(np.int64)
    vals = rng.normal(size=nnz).astype(np.float32)
    P = build_pallas_matrix(rows, cols, vals, N, D)
    print(f"a_f={P.a_f} depth={P.depth_f} slots/entry="
          f"{P.f_code.size / nnz:.2f}")
    for mode in ("dma", "nogather", "full"):
        run_mode(mode, P)


if __name__ == "__main__":
    main()
