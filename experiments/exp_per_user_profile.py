"""Profile the GAME per_user coordinate on the chip: train vs score split,
and sensitivity to max_iters / history (the L-BFGS sequential step count).
Replicates the bench's zipf workload exactly.

Measured 2026-07-31 (round 4): tight bucket padding cut train 575 -> 383 ms (max_iters=10).
Round 5 outcome: the per-bucket breakdown this experiment led to showed the
small-R buckets launch-bound, not FLOPs-bound (E=27k R=4 cost 2x E=13k
R=16) — landed as the batched damped-Newton block solver
(game/coordinates.py newton_block; CG Hessian solves, HIGHEST-precision
small einsums): train 290 -> 75 ms, GAME CD 2.25 -> 4.7 it/s.
"""
import sys, time
import numpy as np
import scipy.sparse as sp

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
from photon_ml_tpu.game.data import build_random_effect_dataset
from photon_ml_tpu.optim.problem import GlmOptimizationConfig, OptimizerConfig
from photon_ml_tpu.optim.regularization import RegularizationContext

rng = np.random.default_rng(1)
ENTITIES, ROW_CAP, RE_DIM = 100_000, 128, 8
sizes = np.minimum(rng.zipf(1.8, ENTITIES), ROW_CAP)
n = int(sizes.sum())
users = np.repeat(
    np.array([f"u{i}" for i in range(ENTITIES)], dtype=object), sizes
)[rng.permutation(n)]
Xu = sp.csr_matrix(rng.normal(size=(n, RE_DIM)).astype(np.float32))
y = (rng.uniform(size=n) < 0.5).astype(np.float32)
re_ds = build_random_effect_dataset(
    users, Xu, y, np.ones(n, np.float32), bucket_growth=4.0
)
print(f"{n} rows, buckets:",
      [(b.n_entities, b.rows_per_entity) for b in re_ds.blocks])

def timed(label, fn, sync, reps=4):
    fn(); jax.block_until_ready(sync(fn()))
    np.asarray(jax.tree.leaves(fn())[0]).ravel()[:1]
    best = np.inf
    for _ in range(reps):
        t0 = time.perf_counter()
        out = fn()
        np.asarray(jax.tree.leaves(out)[0].ravel()[0:1])
        best = min(best, time.perf_counter() - t0)
    print(f"  {label}: {best*1e3:.0f} ms")
    return best

offsets = jnp.zeros(n, jnp.float32)
for mi in (10, 5):
    opt = GlmOptimizationConfig(
        optimizer=OptimizerConfig(max_iters=mi, tolerance=1e-6),
        regularization=RegularizationContext.l2(),
    )
    re = RandomEffectCoordinate("per_user", re_ds, "logistic", opt,
                                reg_weight=1.0, entity_key="userId")
    print(f"max_iters={mi}:")
    t_train = timed("train (all buckets, one jit)",
                    lambda: re.train(offsets), lambda o: o[0])
    state = re.train(offsets)
    t_score = timed("score", lambda: re.score(state), lambda o: o)
    warm = timed("train warm-started",
                 lambda: re.train(offsets, warm_state=state), lambda o: o[0])
