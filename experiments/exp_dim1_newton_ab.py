"""Chip A/B: D=1 scalar-Newton path vs the generic vmapped L-BFGS
(forced via a padded second feature column) on a MovieLens-shaped
per-user bias random effect (100k zipf entities).

Measured 2026-07-31 (round 4): scalar Newton 84 ms vs generic 204 ms = 2.4x.
"""
import sys, time
import numpy as np
import scipy.sparse as sp

sys.path.insert(0, "/root/repo")
import jax
import jax.numpy as jnp
from photon_ml_tpu.game.coordinates import RandomEffectCoordinate
from photon_ml_tpu.game.data import build_random_effect_dataset
from photon_ml_tpu.optim.problem import GlmOptimizationConfig, OptimizerConfig
from photon_ml_tpu.optim.regularization import RegularizationContext

rng = np.random.default_rng(1)
ENTITIES, ROW_CAP = 100_000, 128
sizes = np.minimum(rng.zipf(1.8, ENTITIES), ROW_CAP)
n = int(sizes.sum())
users = np.repeat(
    np.array([f"u{i}" for i in range(ENTITIES)], dtype=object), sizes
)[rng.permutation(n)]
y = (rng.uniform(size=n) < 0.5).astype(np.float32)
opt = GlmOptimizationConfig(
    optimizer=OptimizerConfig(max_iters=10, tolerance=1e-6),
    regularization=RegularizationContext.l2(),
)
offsets = jnp.zeros(n, jnp.float32)

def run(label, X):
    ds = build_random_effect_dataset(
        users, X, y, np.ones(n, np.float32), bucket_growth=4.0
    )
    re = RandomEffectCoordinate("per_user", ds, "logistic", opt,
                                reg_weight=1.0, entity_key="userId")
    re.train(offsets)  # compile + warm
    best = np.inf
    for _ in range(4):
        t0 = time.perf_counter()
        st = re.train(offsets)
        np.asarray(jax.tree.leaves(st)[0].ravel()[0:1])
        best = min(best, time.perf_counter() - t0)
    print(f"{label}: {best*1e3:.0f} ms  dims="
          f"{[(b.n_entities, b.rows_per_entity, b.block_dim) for b in ds.blocks]}")
    return best, st

bias = sp.csr_matrix(np.ones((n, 1), np.float32))
t1, st1 = run("D=1 (scalar Newton)", bias)
two = sp.csr_matrix(np.hstack([
    np.ones((n, 1), np.float32),
    np.full((n, 1), 1e-8, np.float32),  # forces D=2 -> generic L-BFGS
]))
t2, st2 = run("D=2 (generic vmapped L-BFGS)", two)
print(f"speedup {t2/t1:.1f}x")
# Same solutions (the dummy column contributes ~nothing)
a = np.concatenate([np.asarray(b)[:, 0].ravel() for b in st1])
b_ = np.concatenate([np.asarray(b)[:, 0].ravel() for b in st2])
print("max |w_dim1 - w_generic| =", float(np.max(np.abs(np.sort(a) - np.sort(b_)))))
