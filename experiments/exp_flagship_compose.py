"""Flagship composed-shape chip drive: BASELINE config 5 through the API.

One `GameEstimator.fit` over fixed + per_user + per_item + per_context
coordinates at the bench's chip-scale geometry (646k rows, zipf users and
items, few heavy capped contexts), with the context coordinate trained
OUT-OF-CORE under a deliberately small device budget and per-update
train/validation metrics computed ON DEVICE (scalars-only pullback,
riding the CD flush's single batched readback).  This is the shape the
north star cares about, driven end-to-end through the public estimator
API on the real chip — not a hand-assembled CoordinateDescent.

Round-5 continuation session result (chip 25-27 GB/s, RT ~105 ms):
see the printout recorded in ROUND5.md.
"""

import sys
import time

import numpy as np
import scipy.sparse as sp

sys.path.insert(0, "/root/repo")

from photon_ml_tpu.game.estimator import (  # noqa: E402
    FixedEffectCoordinateConfig,
    GameEstimator,
    RandomEffectCoordinateConfig,
)
from photon_ml_tpu.optim.problem import (  # noqa: E402
    GlmOptimizationConfig,
    OptimizerConfig,
)
from photon_ml_tpu.optim.regularization import RegularizationContext  # noqa: E402

rng = np.random.default_rng(3)
ENTITIES, ROW_CAP, RE_DIM = 100_000, 128, 8
FIXED_FEATURES, FIXED_NNZ = 512, 8

sizes = np.minimum(rng.zipf(1.8, ENTITIES), ROW_CAP)
n = int(sizes.sum())
users = np.repeat(
    np.array([f"u{i}" for i in range(ENTITIES)], dtype=object), sizes
)[rng.permutation(n)]
n_items = ENTITIES // 5
item_pool = np.repeat(
    np.array([f"i{i}" for i in range(n_items)], dtype=object),
    np.minimum(rng.zipf(1.5, n_items), 4 * ROW_CAP),
)
items = item_pool[rng.integers(0, len(item_pool), size=n)]
contexts = np.array([f"c{rng.integers(200)}" for _ in range(n)], dtype=object)

nnzf = n * FIXED_NNZ
Xg = sp.csr_matrix(
    (rng.normal(size=nnzf).astype(np.float32),
     (np.repeat(np.arange(n, dtype=np.int64), FIXED_NNZ),
      rng.integers(0, FIXED_FEATURES, size=nnzf))),
    shape=(n, FIXED_FEATURES),
)
y = (rng.uniform(size=n) < 0.5).astype(np.float32)
shards = {
    "global": Xg,
    "user": sp.csr_matrix(rng.normal(size=(n, RE_DIM)).astype(np.float32)),
    "item": sp.csr_matrix(rng.normal(size=(n, RE_DIM)).astype(np.float32)),
    "ctx": sp.csr_matrix(rng.normal(size=(n, RE_DIM)).astype(np.float32)),
}
ids = {"userId": users, "itemId": items, "ctxId": contexts}

opt = GlmOptimizationConfig(
    optimizer=OptimizerConfig(max_iters=10, tolerance=1e-6),
    regularization=RegularizationContext.l2(),
)
configs = {
    "fixed": FixedEffectCoordinateConfig("global", opt, reg_weight=1.0),
    "per_user": RandomEffectCoordinateConfig(
        "user", "userId", opt, reg_weight=1.0
    ),
    "per_item": RandomEffectCoordinateConfig(
        "item", "itemId", opt, reg_weight=1.0
    ),
    # The context coordinate trains OUT-OF-CORE: 8 MiB budget forces
    # multiple budget-bounded pass groups through HBM.
    "per_context": RandomEffectCoordinateConfig(
        "ctx", "ctxId", opt, reg_weight=1.0, max_rows_per_entity=256,
        device_budget_bytes=8 << 20,
    ),
}

def one_fit(cfgs, n_iter):
    est = GameEstimator(
        "logistic", cfgs, n_iterations=n_iter, device_metrics=True
    )
    t0 = time.perf_counter()
    model, history = est.fit(
        shards, ids, y, validation=(shards, ids, y)
    )
    return time.perf_counter() - t0, model, history


resident = dict(configs)
resident["per_context"] = RandomEffectCoordinateConfig(
    "ctx", "ctxId", opt, reg_weight=1.0, max_rows_per_entity=256,
)

print(f"{n} rows; fixed {FIXED_FEATURES}f/{FIXED_NNZ}nnz; "
      f"user/item/ctx REs; device metrics on; 3 CD iterations, "
      "validated per update")
for label, cfgs in (("resident ctx", resident), ("OOC ctx (8 MiB)", configs)):
    one_fit(cfgs, 3)  # compile + warm-in
    walls = []
    for _ in range(3):
        wall, model, hist = one_fit(cfgs, 3)
        walls.append(wall)
    # The whole-fit wall is what an API user experiences: host grouping
    # + h2d + 12 validated coordinate updates.  Transfer rates through
    # the tunnel swing minute-to-minute, hence the median of 3; the
    # OOC-vs-resident gap is the context dataset re-crossing h2d every
    # pass (~100x cheaper on PCIe-attached production hosts).
    per_update = [h for h in hist if "validation_metric" in h]
    print(f"{label}: fit wall median {np.median(walls):.1f}s "
          f"(runs {', '.join(f'{w:.1f}' for w in walls)}); "
          f"train/val AUC {hist[-1]['train_metric']:.4f}/"
          f"{hist[-1]['validation_metric']:.4f}")
    assert len(hist) == 3 * 4
    assert all(type(h["validation_metric"]) is float for h in per_update)
