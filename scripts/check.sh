#!/usr/bin/env bash
# Repo-local check: telemetry selfcheck + the tier-1 test suite.
#
#   scripts/check.sh            # selfcheck + full tier-1 (CPU backend)
#   scripts/check.sh --fast     # selfcheck + the telemetry/watchdog tests
#
# The selfcheck (python -m photon_ml_tpu.telemetry --selfcheck) pushes a
# synthetic span tree through every sink and validates events.jsonl /
# trace.json / metrics.json; it is device-free and takes < 1 s, so run
# it first — a broken sink should fail in seconds, not after the suite.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== telemetry selfcheck =="
python -m photon_ml_tpu.telemetry --selfcheck

# Metric-name lint: every registered metric name in the source tree
# conforms to <subsystem>_<name>_<unit> and no name is used as two
# different kinds (now the analysis/ metric-naming rule; this entry
# point is a thin alias kept for muscle memory).
echo "== telemetry metric-name lint =="
python -m photon_ml_tpu.telemetry --lint-metrics

# Project-wide invariant checker (docs/analysis.md): thread lifecycle /
# lock discipline / wall-clock hygiene, JAX donation + purity, chaos-
# site and metric-name registry sync.  Device-free, AST-only, ~2 s;
# fails on any finding outside the committed baseline.
echo "== analysis invariant check =="
python -m photon_ml_tpu.analysis --check

# The serving selfcheck runs three passes: the single-runtime pass
# builds a synthetic GAME model, serves concurrent HTTP requests, and
# verifies batched results are bit-identical to single-request scoring
# (plus the telemetry snapshot contents); the HA pass kills one of two
# replicas and hot-swaps v1->v2 under live load (plus a tampered-model
# rollback), gating on ZERO failed requests and a monotone
# serving_model_version; the tenancy pass replays the noisy_neighbor
# scenario — an aggressor tenant bursting 10x its quota sheds alone
# while the victim tenant's p99 holds inside its SLO with zero failures.
echo "== serving selfcheck (JAX_PLATFORMS=cpu) =="
env JAX_PLATFORMS=cpu python -m photon_ml_tpu.serving --selfcheck

# The process-mode serving selfcheck runs the same contracts against
# crash-isolated worker PROCESSES on one shared-memory model: score
# parity with in-process scoring, a real SIGKILL under open-loop load
# with zero failed requests, a cross-process hot swap + rollback
# (bit-identical), single-publication segment accounting, and a
# leak-free shutdown under a strict ProcessLeakSentinel — then the
# noisy-neighbor tenancy pass with the tenant id riding the worker
# wire protocol (victim zero-failures gate in process mode too).
echo "== serving process-mode selfcheck (JAX_PLATFORMS=cpu) =="
env JAX_PLATFORMS=cpu python -m photon_ml_tpu.serving --selfcheck --workers 2

# The tuning selfcheck runs a parallel ASHA+GP search on a synthetic
# GAME workload, kills it mid-flight, resumes from tuning_state.jsonl,
# and asserts the resumed trial history + journal decision sequence are
# identical to an uninterrupted run (plus executor crash/retry paths
# and the tuning telemetry contract).
echo "== tuning selfcheck (JAX_PLATFORMS=cpu) =="
env JAX_PLATFORMS=cpu python -m photon_ml_tpu.tuning --selfcheck

# The chaos selfcheck runs the scripted kill/resume/degrade scenario:
# a streamed GLM grid and a GAME CD run killed mid-flight resume
# bitwise-identically through the watchdog, a mid-pass streaming fault
# tears down cleanly, a device-lost fault degrades serving with zero
# request errors and the breaker re-promotes, and checkpoint corruption
# falls back / raises pointed errors (docs/robustness.md).
echo "== chaos selfcheck (JAX_PLATFORMS=cpu) =="
env JAX_PLATFORMS=cpu python -m photon_ml_tpu.chaos --selfcheck

# The freshness selfcheck runs the whole continuous train->serve loop:
# labeled events from a drifted truth model online-refine the serving
# model, the refinement delta-publishes crash-safely and hot-applies to
# a live 2-replica service MID-SCENARIO under open-loop load, gating on
# zero failed requests, bitwise parity with a full reload of the
# refined model, one-step rollback, and the event->servable freshness
# SLO landing in metrics.json (docs/freshness.md).
echo "== freshness selfcheck (JAX_PLATFORMS=cpu) =="
env JAX_PLATFORMS=cpu python -m photon_ml_tpu.freshness --selfcheck

# The cluster selfcheck replays the 3-host control-plane drill under
# open-loop load: the leader quota-coordinator replica is killed and a
# peer takes over within one lease TTL (over-admission bounded to one
# lease window by the journal replay), a cold host bootstraps from the
# newest snapshot publication over HTTP (checksums end to end, scores
# bit-identical) and joins via the membership registry while another
# host drains — zero failed requests throughout (docs/serving.md
# "Cluster").
echo "== cluster selfcheck (JAX_PLATFORMS=cpu) =="
env JAX_PLATFORMS=cpu python -m photon_ml_tpu.cluster --selfcheck

echo "== tier-1 tests (JAX_PLATFORMS=cpu) =="
if [[ "${1:-}" == "--fast" ]]; then
  # Streaming-parity smoke rides the fast lane: a tiny 4-chunk store,
  # asserting the windowed-async pipeline is BIT-IDENTICAL to the
  # depth=1 serial baseline (value/grad, hvp, scores) — the invariant
  # every other streamed number rests on.  The transfer-avoidance smoke
  # repeats the same 4-chunk parity with compressed wire chunks + the
  # hot working-set cache enabled.  test_chaos's kill/resume
  # boundary matrices are the fast recovery smoke.  The fleet smoke is
  # a 2-host router with a scripted host kill under in-flight load:
  # zero failed requests, the killed host rejoins.  test_serving_wire
  # is the binary-parity smoke: a 3-bucket synthetic model scored over
  # live HTTP in both wire formats must produce BITWISE-identical
  # scores (plus fused-kernel parity and frame refusal tests).  The
  # solver smoke pins registry dispatch (explicit --solver lbfgs is
  # bitwise the implicit routing) and consensus-ADMM landing within
  # 1e-5 of the resident OWL-QN optimum over logical shards.
  # test_cluster covers the control plane: membership expiry/heal,
  # coordinator leader failover + journal replay, and checksum-verified
  # publication fetch (all three cluster.* chaos seams).  The
  # hierarchical-GAME smoke runs one sharded-vs-single parity leg on
  # the forced multi-device mesh (resident + out-of-core, BITWISE) —
  # the invariant the mesh bucket-shard plan rests on.
  exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_telemetry.py tests/test_ops_plane.py \
    tests/test_watchdog.py \
    tests/test_serving.py tests/test_serving_ha.py \
    tests/test_serving_proc.py tests/test_freshness.py \
    tests/test_serving_wire.py \
    tests/test_distributed_tracing.py \
    tests/test_cluster.py \
    tests/test_tuning.py tests/test_chaos.py \
    "tests/test_streaming.py::TestPipelineParity::test_async_window_bit_identical_to_sync_f32" \
    "tests/test_streaming.py::TestTransferAvoidance::test_fast_lane_compressed_cached_parity" \
    "tests/test_serving_fleet.py::TestFleetRouter::test_host_kill_under_load_costs_zero_failures" \
    "tests/test_solvers.py::TestDispatchParity::test_resident_bitwise" \
    "tests/test_solvers.py::TestADMM::test_logical_shards_match_owlqn" \
    "tests/test_game_hierarchical.py::TestShardedParity::test_resident_bitwise[per_user-shape0]" \
    "tests/test_game_hierarchical.py::TestShardedParity::test_out_of_core_bitwise[per_user-shape0]" \
    -m 'not slow' -q -p no:cacheprovider
fi
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly
