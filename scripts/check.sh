#!/usr/bin/env bash
# Repo-local check: telemetry selfcheck + the tier-1 test suite.
#
#   scripts/check.sh            # selfcheck + full tier-1 (CPU backend)
#   scripts/check.sh --fast     # selfcheck + the telemetry/watchdog tests
#
# The selfcheck (python -m photon_ml_tpu.telemetry --selfcheck) pushes a
# synthetic span tree through every sink and validates events.jsonl /
# trace.json / metrics.json; it is device-free and takes < 1 s, so run
# it first — a broken sink should fail in seconds, not after the suite.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== telemetry selfcheck =="
python -m photon_ml_tpu.telemetry --selfcheck

# The serving selfcheck builds a synthetic GAME model, serves concurrent
# HTTP requests, and verifies batched results are bit-identical to
# single-request scoring (plus the telemetry snapshot contents).
echo "== serving selfcheck (JAX_PLATFORMS=cpu) =="
env JAX_PLATFORMS=cpu python -m photon_ml_tpu.serving --selfcheck

# The tuning selfcheck runs a parallel ASHA+GP search on a synthetic
# GAME workload, kills it mid-flight, resumes from tuning_state.jsonl,
# and asserts the resumed trial history + journal decision sequence are
# identical to an uninterrupted run (plus executor crash/retry paths
# and the tuning telemetry contract).
echo "== tuning selfcheck (JAX_PLATFORMS=cpu) =="
env JAX_PLATFORMS=cpu python -m photon_ml_tpu.tuning --selfcheck

echo "== tier-1 tests (JAX_PLATFORMS=cpu) =="
if [[ "${1:-}" == "--fast" ]]; then
  exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_telemetry.py tests/test_watchdog.py \
    tests/test_serving.py tests/test_tuning.py -m 'not slow' \
    -q -p no:cacheprovider
fi
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
  --continue-on-collection-errors -p no:cacheprovider \
  -p no:xdist -p no:randomly
